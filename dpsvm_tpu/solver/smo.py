"""Single-chip jitted SMO engine.

TPU-native re-design of class SvmTrain (svmTrain.h:48-140, svmTrain.cu):
the reference runs each SMO iteration as a host-driven sequence of GPU
launches (classify for_each, min/max reduce, cublas sgemv, f-update
for_each) with a device->host sync every iteration (svmTrain.cu:469-499,
svmTrainMain.cpp:235-310). Here the ENTIRE iteration — selection, kernel
rows (with HBM cache), alpha-pair algebra and f update — is one
``lax.while_loop`` body compiled once by XLA; the host only observes state
between chunks of ``config.chunk_iters`` iterations (for convergence
reporting, metrics and checkpointing; SURVEY.md section 7.3 item 6).
"""

from __future__ import annotations

import time
from functools import partial
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

from dpsvm_tpu.config import SVMConfig
from dpsvm_tpu.ops.kernels import (
    KernelParams,
    kernel_diag,
    kernel_from_dots,
    kernel_rows,
    row_dots,
    squared_norms,
)
from dpsvm_tpu.ops.select import (c_of, low_mask, refresh_extrema_host,
                                  select_working_set,
                                  select_working_set_nu, split_c, up_mask)
from dpsvm_tpu.solver.cache import CacheState, init_cache, lookup_one, lookup_pair
from dpsvm_tpu.solver.result import SolveResult
from dpsvm_tpu.testing import faults


class SMOState(NamedTuple):
    """while_loop carry. Mirrors SvmTrain's device-resident solver state
    (g_alpha/g_f, svmTrain.cu:349,380) plus convergence scalars and the
    kernel-row cache."""

    alpha: jax.Array  # (n,) float32
    f: jax.Array  # (n,) float32, f_i = sum_j a_j y_j K_ij - y_i
    b_hi: jax.Array  # float32
    b_lo: jax.Array  # float32
    it: jax.Array  # int32
    cache: CacheState
    hits: jax.Array  # int32 cache-hit count (observability, SURVEY 5.5)
    # Kahan residual of f (config.compensated): true f ~= f - f_err.
    # None (an empty pytree leaf) when compensation is off, so existing
    # constructors, shard_map specs and compiled carries are unchanged.
    f_err: Optional[jax.Array] = None


def init_state(n: int, y: jax.Array, cache_lines: int) -> SMOState:
    return SMOState(
        alpha=jnp.zeros((n,), jnp.float32),
        f=(-y).astype(jnp.float32),  # f = -y at alpha = 0 (svmTrain.cu:380)
        b_hi=jnp.float32(-jnp.inf),
        b_lo=jnp.float32(jnp.inf),  # do-while: first chunk always enters
        it=jnp.int32(0),
        cache=init_cache(cache_lines, n),
        hits=jnp.int32(0),
    )


def eff_f(state):
    """The solver's best estimate of the true gradient: f minus the Kahan
    residual when compensation is on (SMOState/BlockState both carry the
    trailing f_err leaf). Works on device arrays and host-pulled state."""
    return state.f if state.f_err is None else state.f - state.f_err


def kahan_add(f, err, delta):
    """One compensated (Kahan) vector accumulation step: returns the new
    (f, err) with the invariant true_sum ~= f - err.

    Why it exists (config.compensated): at extreme C the rank-2 f updates
    add terms of magnitude up to C*|K| ~ 2048 to values of order 1; each
    fp32 add rounds by ~eps*|term| ~ 1e-4 and the solver's incremental
    gradient random-walks away from the true one (measured: carried gap
    0.005 vs true 1.1 after 8M pairs — PARITY.md covtype section). The
    compensation defers each step's rounding into `err`, cutting the
    accumulated drift to second order, so the carried gap stays honest
    without the external reconstruction harness. Cost: 3 extra
    elementwise vector ops per update — noise on a latency-bound chain.
    No reference equivalent (the reference's fp32 gradient silently
    drifts the same way, svmTrain.cu:98-137)."""
    y_v = delta - err
    t = f + y_v
    return t, (t - f) - y_v


def maybe_kahan(f, err, delta):
    """Fold `delta` into (f, err): plain add when compensation is off
    (err is None), Kahan-compensated otherwise. The single definition of
    the conditional every engine's fold uses."""
    if err is None:
        return f + delta, None
    return kahan_add(f, err, delta)


def pair_alpha_update(a_hi_old, a_lo_old, y_hi, y_lo, b_hi_pair, b_lo_pair,
                      eta, c_hi, c_lo=None, gate=None):
    """THE alpha-pair algebra, shared by the XLA, Pallas and distributed
    engines. Returns (a_hi_new, a_lo_new). `c_hi`/`c_lo` are the box upper
    bounds of the two variables (they differ under class-weighted C,
    LibSVM's -w; pass one value for the unweighted case).

    Deliberate divergence from the reference (svmTrainMain.cpp:285-299,
    seq.cpp:237-250): the reference clips a_lo to [0, C] and then clips
    a_hi to [0, C] *independently*. Whenever that second clip actually
    triggers, delta(a_hi) != -s * delta(a_lo) and the dual equality
    constraint sum_i alpha_i y_i = const is silently violated — the drift
    accumulates and biases b (it is what made the one-class reduction,
    whose alphas start AT the bound, end up with sum alpha != nu*n).
    The standard (Platt) form used here clips a_lo to the joint feasible
    segment [L, H] of the box intersected with the constraint line, after
    which a_hi stays in box by construction and conservation is exact:
        s = y_hi*y_lo, w = a_hi_old + s*a_lo_old
        s=+1: L = max(0, w - C_hi),  H = min(C_lo, w)
        s=-1: L = max(0, -w),        H = min(C_lo, C_hi - w)

    `gate` (bool scalar) forces an exact no-op when False — used when a
    selection round found no admissible pair (empty I_up/I_low after alpha
    hit the bounds), where the +-inf sentinels would otherwise clip alpha
    to a bound and desynchronize f from alpha. Non-finite pair values are
    always gated out.
    """
    if c_lo is None:
        c_lo = c_hi
    ok = jnp.isfinite(b_hi_pair) & jnp.isfinite(b_lo_pair)
    if gate is not None:
        ok = ok & gate
    s = y_hi * y_lo
    w = a_hi_old + s * a_lo_old
    lo_bound = jnp.where(s > 0, jnp.maximum(0.0, w - c_hi), jnp.maximum(0.0, -w))
    hi_bound = jnp.where(s > 0, jnp.minimum(c_lo, w), jnp.minimum(c_lo, c_hi - w))
    a_lo_new = jnp.clip(a_lo_old + y_lo * (b_hi_pair - b_lo_pair) / eta,
                        lo_bound, hi_bound)
    # Snap to the box bounds (LibSVM assigns exact bound constants in its
    # clip branches): round-off in w can leave an alpha at c - 1ulp, which
    # the I_up/I_low masks still admit while the joint feasible segment has
    # ~ulp width — a selectable pair with a zero step, i.e. a livelock.
    # a_lo is snapped BEFORE a_hi is derived from it so the derivation
    # keeps delta(a_hi) = -s * delta(a_lo) (conservation); a_hi's own snap
    # then only absorbs rounding of the derivation itself.
    snap_lo = 1e-6 * c_lo
    snap_hi = 1e-6 * c_hi
    a_lo_new = jnp.where(a_lo_new < snap_lo, 0.0,
                         jnp.where(a_lo_new > c_lo - snap_lo, c_lo, a_lo_new))
    # In box by construction; the final clip only absorbs float round-off.
    a_hi_new = jnp.clip(a_hi_old + s * (a_lo_old - a_lo_new), 0.0, c_hi)
    a_hi_new = jnp.where(a_hi_new < snap_hi, 0.0,
                         jnp.where(a_hi_new > c_hi - snap_hi, c_hi, a_hi_new))
    a_lo_new = jnp.where(ok, a_lo_new, a_lo_old)
    a_hi_new = jnp.where(ok, a_hi_new, a_hi_old)
    return a_hi_new, a_lo_new


def _apply_pair_update(state: SMOState, y, i_hi, i_lo, b_hi_pair, b_lo_pair,
                       k_hi, k_lo, eta, c, gate=None) -> tuple:
    """Shared tail of an SMO iteration: alpha-pair algebra + rank-2 f
    update (update_functor svmTrain.cu:98-137). `c` is (c_pos, c_neg).
    Returns (alpha, f, f_err) — f_err is None unless the state carries a
    Kahan residual (config.compensated), in which case the rank-2 delta
    is accumulated compensated (see kahan_add)."""

    cp, cn = split_c(c)
    y_hi = y[i_hi].astype(jnp.float32)
    y_lo = y[i_lo].astype(jnp.float32)
    a_hi_old = state.alpha[i_hi]
    a_lo_old = state.alpha[i_lo]
    a_hi_new, a_lo_new = pair_alpha_update(
        a_hi_old, a_lo_old, y_hi, y_lo, b_hi_pair, b_lo_pair, eta,
        c_of(y_hi, cp, cn), c_of(y_lo, cp, cn), gate)
    alpha = state.alpha.at[i_lo].set(a_lo_new).at[i_hi].set(a_hi_new)
    if state.f_err is None:
        # Left-to-right association kept bit-identical to the
        # pre-compensation engine (tolerances in the parity artifacts are
        # calibrated against this exact rounding sequence).
        f = state.f + (a_hi_new - a_hi_old) * y_hi * k_hi \
                    + (a_lo_new - a_lo_old) * y_lo * k_lo
        return alpha, f, None
    delta = (a_hi_new - a_hi_old) * y_hi * k_hi \
        + (a_lo_new - a_lo_old) * y_lo * k_lo
    f, err = kahan_add(state.f, state.f_err, delta)
    return alpha, f, err


def _smo_iteration(x, y, x_sq, k_diag, valid, state: SMOState, kp: KernelParams,
                   c: float, tau: float, use_cache: bool,
                   select_fn=select_working_set) -> SMOState:
    """One reference-parity (maximal-violating-pair) SMO iteration.

    `select_fn` swaps the working-set rule: the default is the C-SVC
    global MVP; `select_working_set_nu` restricts the pair to one class
    (the nu duals' two-equality-constraint variant) — everything after
    selection (kernel rows, pair algebra, f update) is identical.
    """
    i_hi, b_hi, i_lo, b_lo = select_fn(eff_f(state), state.alpha, y, c, valid)

    q_hi = lax.dynamic_index_in_dim(x, i_hi, 0, keepdims=False)
    q_lo = lax.dynamic_index_in_dim(x, i_lo, 0, keepdims=False)
    if kp.kind == "precomputed":
        # x IS the Gram matrix: the gathered rows already hold K values
        # (no dot products or cache; config forbids cache_lines here).
        k_hi = q_hi.astype(jnp.float32)
        k_lo = q_lo.astype(jnp.float32)
        cache, n_hits = state.cache, jnp.int32(0)
    else:
        if use_cache:
            d_hi, d_lo, cache, n_hits = lookup_pair(
                state.cache, x, i_hi, i_lo, q_hi, q_lo, state.it)
        else:
            d2 = row_dots(x, jnp.stack([q_hi, q_lo]))
            d_hi, d_lo, cache, n_hits = d2[0], d2[1], state.cache, jnp.int32(0)

        k_hi = kernel_from_dots(d_hi, x_sq, x_sq[i_hi], kp)
        k_lo = kernel_from_dots(d_lo, x_sq, x_sq[i_lo], kp)

    # eta = K(hi,hi) + K(lo,lo) - 2 K(hi,lo), clamped (fixes bug B2; the
    # reference divides unguarded at svmTrainMain.cpp:290).
    eta = jnp.maximum(k_hi[i_hi] + k_lo[i_lo] - 2.0 * k_hi[i_lo], tau)

    alpha, f, f_err = _apply_pair_update(state, y, i_hi, i_lo, b_hi, b_lo,
                                         k_hi, k_lo, eta, c)
    return SMOState(alpha, f, b_hi, b_lo, state.it + 1, cache,
                    state.hits + n_hits, f_err)


def _smo_iteration_wss2(x, y, x_sq, k_diag, valid, state: SMOState,
                        kp: KernelParams, c: float, tau: float,
                        use_cache: bool) -> SMOState:
    """One second-order (WSS2) iteration: i by max violation, j by max
    second-order gain (f_j - f_i)^2 / eta_ij over eligible I_low.

    No reference equivalent — this is the LibSVM working-set rule, offered
    because the row of kernel values needed for the gain is exactly the
    row the f update fetches anyway, so the extra selection is one more
    O(n) pass for typically several-fold fewer iterations.
    """
    cp, cn = split_c(c)
    f_cur = eff_f(state)
    up = up_mask(state.alpha, y, cp, cn)
    low = low_mask(state.alpha, y, cp, cn)
    if valid is not None:
        up = up & valid
        low = low & valid
    f_up = jnp.where(up, f_cur, jnp.inf)
    f_low = jnp.where(low, f_cur, -jnp.inf)
    i_hi = jnp.argmin(f_up).astype(jnp.int32)
    b_hi = f_up[i_hi]
    b_lo = jnp.max(f_low)  # convergence gap still uses the max violator

    q_hi = lax.dynamic_index_in_dim(x, i_hi, 0, keepdims=False)
    stamp = 2 * state.it.astype(jnp.int32)
    if kp.kind == "precomputed":
        k_hi, cache, hit_hi = (q_hi.astype(jnp.float32), state.cache,
                               jnp.bool_(False))
    elif use_cache:
        d_hi, cache, hit_hi = lookup_one(state.cache, x, i_hi, q_hi, stamp + 1)
        k_hi = kernel_from_dots(d_hi, x_sq, x_sq[i_hi], kp)
    else:
        d_hi, cache, hit_hi = row_dots(x, q_hi), state.cache, jnp.bool_(False)
        k_hi = kernel_from_dots(d_hi, x_sq, x_sq[i_hi], kp)

    diff = f_cur - b_hi  # f_j - f_i
    eta_j = jnp.maximum(k_diag[i_hi] + k_diag - 2.0 * k_hi, tau)
    gain = jnp.where(low & (diff > 0), diff * diff / eta_j, -jnp.inf)
    any_elig = jnp.any(gain > -jnp.inf)
    # No eligible j <=> b_lo <= b_hi <=> converged; make the update a no-op
    # by degenerating to i_lo = i_hi (deltas become exactly 0).
    i_lo = jnp.where(any_elig, jnp.argmax(gain), i_hi).astype(jnp.int32)
    b_lo_pair = f_cur[i_lo]

    q_lo = lax.dynamic_index_in_dim(x, i_lo, 0, keepdims=False)
    if kp.kind == "precomputed":
        k_lo, hit_lo = q_lo.astype(jnp.float32), jnp.bool_(False)
    elif use_cache:
        d_lo, cache, hit_lo = lookup_one(cache, x, i_lo, q_lo, stamp + 2)
        k_lo = kernel_from_dots(d_lo, x_sq, x_sq[i_lo], kp)
    else:
        d_lo, hit_lo = row_dots(x, q_lo), jnp.bool_(False)
        k_lo = kernel_from_dots(d_lo, x_sq, x_sq[i_lo], kp)

    eta = jnp.maximum(k_diag[i_hi] + k_diag[i_lo] - 2.0 * k_hi[i_lo], tau)
    n_hits = hit_hi.astype(jnp.int32) + hit_lo.astype(jnp.int32)
    alpha, f, f_err = _apply_pair_update(state, y, i_hi, i_lo, b_hi,
                                         b_lo_pair, k_hi, k_lo, eta, c,
                                         gate=any_elig)
    return SMOState(alpha, f, b_hi, b_lo, state.it + 1, cache,
                    state.hits + n_hits, f_err)


_ITERATION_FNS = {
    "mvp": _smo_iteration,
    "second_order": _smo_iteration_wss2,
    # Internal: per-class MVP for the nu duals (set by models/nusvm.py's
    # trainers, not meant as a user-facing selection rule for C-SVC).
    "nu": partial(_smo_iteration, select_fn=select_working_set_nu),
}

# Chunk length used when nothing on the host needs to observe intermediate
# state (no callback / verbose / checkpoint / numerics checks): the loop
# then runs to convergence-or-max_iter in ONE dispatch. A fixed sentinel —
# not max_iter — so the compiled program is independent of max_iter (which
# stays a traced scalar) and a short warm-up run compiles the same
# executable as the real run. Device->host observation is expensive on
# disaggregated/tunneled TPU runtimes (~80 ms per transfer measured on the
# dev harness), so the default is to observe only once, at the end.
_UNOBSERVED_CHUNK = 1 << 30

# config.budget_mode compiles the chunk executors with this epsilon: the
# stopping test b_lo > b_hi + 2*eps then never closes (the gap is bounded
# well above 2*-1e30), so the loop exits exactly at the max_iter budget —
# the reference's own benchmark regime (its published runs are
# max_iter-capped, reference Makefile:74,77). Finite so the b_hi + 2*eps
# arithmetic stays inf-free.
_BUDGET_EPS = -1e30


@jax.jit
def _pack_obs(it, b_hi, b_lo):
    """Pack (iteration, b_hi, b_lo) into ONE (4,) device array so the host
    loop pays a single device->host transfer per chunk instead of three.
    The int32 iteration rides in two 12/19-bit halves, each exactly
    representable in float32 (a raw bitcast would make small counts
    denormal floats, which the TPU flushes to zero)."""
    it = it.astype(jnp.int32)
    return jnp.stack([
        (it >> 12).astype(jnp.float32),
        (it & 0xFFF).astype(jnp.float32),
        b_hi.astype(jnp.float32),
        b_lo.astype(jnp.float32),
    ])


def _unpack_obs(packed) -> tuple:
    import numpy as np

    arr = np.asarray(packed)
    it = (int(arr[0]) << 12) | int(arr[1])
    return it, float(arr[2]), float(arr[3])


@partial(jax.jit, static_argnames=("kp", "c", "eps", "tau", "chunk",
                                   "use_cache", "block_rows", "interpret"))
def _run_chunk_pallas(x, y, x_sq, valid, state: SMOState, max_iter,
                      kp: KernelParams, c: float, eps: float, tau: float,
                      chunk: int, use_cache: bool, block_rows: int,
                      interpret: bool) -> SMOState:
    """Software-pipelined chunk executor built on the fused Pallas kernel
    (ops/pallas_fused.py): each loop body applies iteration t's rank-2
    update AND computes iteration t+1's selection in one pass over f.

    Requires n padded to a multiple of block_rows*128 with `valid`
    marking real rows. Semantics note: unlike the reference's do-while
    (svmTrainMain.cpp:235-310), the loop stops as soon as a post-update
    selection shows convergence, skipping the reference's final
    degenerate update — iteration counts can differ by one.
    """
    from dpsvm_tpu.ops.pallas_fused import LANES, fused_update_select

    n_pad = y.shape[0]
    rows = n_pad // LANES
    shp = (rows, LANES)
    y2d = y.reshape(shp)
    valid2d = valid.astype(jnp.float32).reshape(shp)
    x_sq2d = x_sq.reshape(shp)

    # Seed selection for the pipelined carry (top-of-iteration values).
    i_hi0, b_hi0, i_lo0, b_lo0 = select_working_set(
        state.f, state.alpha, y, c, valid)
    end = jnp.minimum(state.it + chunk, max_iter)

    def cond(carry):
        st, i_hi, i_lo = carry
        return (st.it < end) & (st.b_lo > st.b_hi + 2.0 * eps)

    def body(carry):
        st, i_hi, i_lo = carry
        q_hi = lax.dynamic_index_in_dim(x, i_hi, 0, keepdims=False)
        q_lo = lax.dynamic_index_in_dim(x, i_lo, 0, keepdims=False)
        if use_cache:
            d_hi, d_lo, cache, n_hits = lookup_pair(
                st.cache, x, i_hi, i_lo, q_hi, q_lo, st.it)
        else:
            d2 = row_dots(x, jnp.stack([q_hi, q_lo]))
            d_hi, d_lo, cache, n_hits = d2[0], d2[1], st.cache, jnp.int32(0)

        qsq_hi = x_sq[i_hi]
        qsq_lo = x_sq[i_lo]
        k_hh = kernel_from_dots(d_hi[i_hi], qsq_hi, qsq_hi, kp)
        k_ll = kernel_from_dots(d_lo[i_lo], qsq_lo, qsq_lo, kp)
        k_hl = kernel_from_dots(d_hi[i_lo], qsq_lo, qsq_hi, kp)
        eta = jnp.maximum(k_hh + k_ll - 2.0 * k_hl, tau)

        cp, cn = split_c(c)
        y_hi = y[i_hi]
        y_lo = y[i_lo]
        a_hi_old = st.alpha[i_hi]
        a_lo_old = st.alpha[i_lo]
        a_hi_new, a_lo_new = pair_alpha_update(
            a_hi_old, a_lo_old, y_hi, y_lo, st.b_hi, st.b_lo, eta,
            c_of(y_hi, cp, cn), c_of(y_lo, cp, cn))
        alpha = st.alpha.at[i_lo].set(a_lo_new).at[i_hi].set(a_hi_new)

        scalars = jnp.stack([
            (a_hi_new - a_hi_old) * y_hi,
            (a_lo_new - a_lo_old) * y_lo,
            qsq_hi, qsq_lo,
        ])
        f2d, b_hi, i_hi_n, b_lo, i_lo_n = fused_update_select(
            st.f.reshape(shp), alpha.reshape(shp), y2d, valid2d,
            d_hi.reshape(shp), d_lo.reshape(shp), x_sq2d, scalars,
            kp, c, block_rows=block_rows, interpret=interpret)

        new_st = SMOState(alpha, f2d.reshape(n_pad), b_hi, b_lo,
                          st.it + 1, cache, st.hits + n_hits)
        return new_st, i_hi_n, i_lo_n

    st0 = state._replace(b_hi=b_hi0, b_lo=b_lo0)
    final, _, _ = lax.while_loop(cond, body, (st0, i_hi0, i_lo0))
    return final


@partial(jax.jit, static_argnames=("kp", "c", "eps", "tau", "chunk", "k"))
def _run_chunk_micro(x, y, x_sq, k_diag, valid, state: SMOState, max_iter,
                     kp: KernelParams, c, eps: float, tau: float,
                     chunk: int, k: int) -> SMOState:
    """Micro-batched per-pair chunk executor (config.pair_batch > 1 on
    engine='xla', mvp selection).

    The plain per-pair loop is LATENCY-bound on TPU: its body is ~10
    serialized small kernels and costs ~22 us/pair even when the kernel
    rows are resident-Gram gathers (measured n=50k v5e, PROFILE.md
    round-5). Each trip here amortizes that fixed cost over k pairs:

      1. ONE selection pass picks the k most-violating disjoint pairs —
         top-k of I_up by smallest f paired rank-for-rank with top-k of
         I_low by largest f (pair 1 is exactly the reference's maximal
         violating pair; pairs 2..k are the pair_batch=2 scheme of
         solver/block.py generalized);
      2. ONE batched pass produces all 2k kernel rows (a (2k, d) x
         (d, n) MXU matvec — or a 2k-row gather in resident-Gram mode);
      3. the k pair updates run as UNROLLED scalar algebra against the
         (2k, 2k) cross-Gram block: selection is stale (rank j), but
         every update's (b_hi, b_lo) are CORRECTED to the
         post-previous-updates gradient, so each applied step is an
         exact descent step on a then-violating pair — same optimum,
         different pair sequence (the pair_batch=2 contract);
      4. ONE rank-2k fold applies the accumulated coefficients to f.

    Pairs j >= 2 gate on fe_lo > fe_hi + 2*eps — the SAME margin as the
    stopping rule (unlike block pair_batch=2's margin-free second slot,
    ADVICE round-4): a sub-tolerance slot is a counted no-op (attempted
    slots count, the block subproblem's pinned budget semantics). A
    free point can top BOTH lists; collisions are resolved by rank
    order (a pair colliding with an earlier APPLIED pair is a counted
    no-op and its stale slots never scatter) so the rank-0 maximal pair
    always executes.
    """
    cp, cn = split_c(c)
    # Clamp the selection's top-k to the (padded) row count: a toy
    # problem with n < pair_batch would otherwise die in an obscure XLA
    # trace error inside top_k (ADVICE round-5, low). Static shapes, so
    # this resolves at trace time; the clamped executor just batches
    # fewer slots per trip — same semantics.
    k = min(k, int(y.shape[0]))
    end = jnp.minimum(state.it + chunk, max_iter)

    def top_pairs(scores):
        """(vals, idx) of the top k per row of the stacked (2, n) scores,
        SORTED descending. One stacked reduction per trip; on TPU the
        exact lax.top_k is ~4x the cost of approx_max_k here (155.8 vs
        41.1 us/trip measured at n=20k, k=8), and approx's bin-max always
        retains each row's true maximum — so after the (trivial, 2k-
        element) sort, slot 0 is the EXACT maximal violating pair and
        the approximation only reshuffles the interchangeable ranks
        2..k (solver/block.py _top_h rationale)."""
        if jax.default_backend() == "tpu":
            v, i = lax.approx_max_k(scores, k)
            order = jnp.argsort(-v, axis=1)
            return jnp.take_along_axis(v, order, axis=1), \
                jnp.take_along_axis(i, order, axis=1)
        return lax.top_k(scores, k)

    def cond(st: SMOState):
        return (st.it < end) & (st.b_lo > st.b_hi + 2.0 * eps)

    def body(st: SMOState):
        f_cur = eff_f(st)
        up = up_mask(st.alpha, y, cp, cn)
        low = low_mask(st.alpha, y, cp, cn)
        if valid is not None:
            up = up & valid
            low = low & valid
        scores = jnp.stack([jnp.where(up, -f_cur, -jnp.inf),
                            jnp.where(low, f_cur, -jnp.inf)])
        vals, ids = top_pairs(scores)
        up_v, up_i = vals[0], ids[0]  # ascending f: rank 0 = b_hi
        low_v, low_i = vals[1], ids[1]  # descending f: rank 0 = b_lo
        b_hi = -up_v[0]
        b_lo = low_v[0]
        up_ok = jnp.isfinite(up_v)
        low_ok = jnp.isfinite(low_v)
        # A free point can appear in BOTH top lists (it is in I_up and
        # I_low at once). Collisions are resolved by RANK ORDER inside
        # the unrolled update loop below — a pair whose member was
        # already touched by an EARLIER applied pair this trip is gated
        # off. A global "drop the low copy" dedup here would be wrong:
        # it can gate off rank 0 — the maximal violating pair and the
        # only slot guaranteed to execute — and livelock the loop into
        # counted no-op trips (review finding, round 5).
        collide = low_i[:, None] == up_i[None, :]  # [low_rank, up_rank]
        idx = jnp.concatenate([up_i, low_i]).astype(jnp.int32)  # (2k,)
        # Row/column extraction via UNROLLED dynamic slices, never
        # jnp.take: XLA lowers a general row gather from a large operand
        # (the resident Gram is (n, n)) to a one-hot MATMUL on TPU —
        # O(k n^2) per trip, measured 606 us/pair at n=20k. 2k dynamic
        # slices are plain DMAs.
        qx = jnp.stack([lax.dynamic_index_in_dim(x, idx[s], 0,
                                                 keepdims=False)
                        for s in range(2 * k)])
        rows = kernel_rows(x, x_sq, qx, jnp.take(x_sq, idx), kp)  # (2k, n)
        m = jnp.stack([lax.dynamic_index_in_dim(rows, idx[s], 1,
                                                keepdims=False)
                       for s in range(2 * k)], axis=1)  # (2k, 2k)
        kd = jnp.take(k_diag, idx)
        a = jnp.take(st.alpha, idx)
        fv = jnp.take(f_cur, idx)
        yv = jnp.take(y, idx)
        coef = jnp.zeros((2 * k,), jnp.float32)
        t = st.it
        applied = []  # per-pair applied gates, for collision tracking
        for j in range(k):  # unrolled: all indices below are static
            i_s, l_s = j, k + j
            ok = up_ok[j] & low_ok[j]
            # Gate on cross-list coordinate collisions with THIS pair
            # (a point on both sides would self-pair) or with any
            # EARLIER APPLIED pair (its alpha scalar here is stale).
            # Rank 0 has no earlier pairs, so the maximal violating
            # pair always executes — the livelock guard (a global
            # drop-the-low-copy dedup could gate it off and spin the
            # loop in counted no-op trips; review finding, round 5).
            bad = collide[j, j]
            for p in range(j):
                bad |= (collide[p, j] | collide[j, p]) & applied[p]
            ok = ok & ~bad
            fe_i = fv[i_s] + coef @ m[:, i_s]  # corrected gradient
            fe_l = fv[l_s] + coef @ m[:, l_s]
            if j == 0:
                # Reference semantics: the selected maximal pair always
                # executes (a closed-gap trip is the do-while loop's
                # final degenerate update) and always counts.
                gate = ok
                cnt = jnp.int32(1)
            else:
                gate = ok & (t < end) & (fe_l > fe_i + 2.0 * eps)
                # ATTEMPTED slots count even when the update gates to a
                # no-op — the block subproblem's pinned pair_batch
                # counting semantics (solver/block.py), and what keeps
                # budget math deterministic.
                cnt = (t < end).astype(jnp.int32)
            eta = jnp.maximum(kd[i_s] + kd[l_s] - 2.0 * m[i_s, l_s], tau)
            na_i, na_l = pair_alpha_update(
                a[i_s], a[l_s], yv[i_s], yv[l_s], fe_i, fe_l, eta,
                c_of(yv[i_s], cp, cn), c_of(yv[l_s], cp, cn), gate=gate)
            coef = coef.at[i_s].add((na_i - a[i_s]) * yv[i_s])
            coef = coef.at[l_s].add((na_l - a[l_s]) * yv[l_s])
            a = a.at[i_s].set(na_i).at[l_s].set(na_l)
            applied.append(gate)
            t = t + cnt
        f, f_err = maybe_kahan(st.f, st.f_err, coef @ rows)
        # Scatter mask. Dead top-k filler never scatters. For a global
        # index that appears in TWO pairs (cross-list collision), at
        # most one of those pairs applied (an applied pair gates every
        # later collider); the UNAPPLIED pair's slots hold stale copies
        # and must not race the applied pair's scatter — drop both its
        # slots (their values are unchanged, so nothing is lost). Two
        # unapplied colliding pairs scatter identical unchanged values,
        # which is benign.
        applied_v = jnp.stack(applied)  # (k,)
        share = collide | collide.T  # pairs p,q share a coordinate
        conflict = ~applied_v & jnp.any(share & applied_v[None, :], axis=1)
        pair_scatter = jnp.tile(~conflict, 2)
        slot_ok = jnp.concatenate([up_ok, low_ok]) & pair_scatter
        safe = jnp.where(slot_ok, idx, jnp.int32(y.shape[0]))
        alpha = st.alpha.at[safe].set(jnp.where(slot_ok, a, 0.0),
                                      mode="drop")
        return SMOState(alpha, f, b_hi, b_lo, t, st.cache, st.hits, f_err)

    return lax.while_loop(cond, body, state)


@partial(jax.jit, static_argnames=("kp", "c", "eps", "tau", "chunk",
                                   "use_cache", "selection"))
def _run_chunk(x, y, x_sq, k_diag, valid, state: SMOState, max_iter,
               kp: KernelParams, c: float, eps: float, tau: float,
               chunk: int, use_cache: bool, selection: str = "mvp") -> SMOState:
    """Run up to `chunk` SMO iterations fully on device."""
    end = jnp.minimum(state.it + chunk, max_iter)
    step = _ITERATION_FNS[selection]

    def cond(st: SMOState):
        return (st.it < end) & (st.b_lo > st.b_hi + 2.0 * eps)

    def body(st: SMOState):
        return step(x, y, x_sq, k_diag, valid, st, kp, c, tau, use_cache)

    return lax.while_loop(cond, body, state)


def assert_finite_state(state: SMOState, it: int, backend: str) -> None:
    """Chunk-boundary sanitizer (config.check_numerics): the functional
    solver cannot race, but bad inputs (inf features, absurd gamma/C) can
    still produce NaN/inf f — fail with context instead of looping to
    max_iter."""
    bad_f = int(jnp.sum(~jnp.isfinite(state.f)))
    bad_a = int(jnp.sum(~jnp.isfinite(state.alpha)))
    if bad_f or bad_a:
        raise FloatingPointError(
            f"[{backend}] non-finite solver state at iteration {it}: "
            f"{bad_f} bad f entries, {bad_a} bad alpha entries — check "
            "input features for inf/NaN and gamma/C scaling")


def _precision_ctx(config: SVMConfig):
    """Scoped matmul-precision override for everything a solve traces and
    dispatches (config.matmul_precision; jax keys its jit caches on this
    context, so configs at different precisions compile separately)."""
    from contextlib import nullcontext

    p = config.resolve_precision()
    return jax.default_matmul_precision(p) if p else nullcontext()


# Markers that identify a TRANSIENT device-runtime fault worth retrying
# (tunneled/disaggregated TPU runtimes fault long dispatches with
# UNAVAILABLE; preemptions surface as ABORTED/CANCELLED). Anything else —
# e.g. INVALID_ARGUMENT from a real bug — propagates immediately. grpc
# status codes match case-sensitively; the prose markers are checked
# lowercase against the lowercased message.
_GRPC_TRANSIENT = ("UNAVAILABLE", "DEADLINE_EXCEEDED", "ABORTED",
                   "CANCELLED", "INTERNAL")
_PROSE_TRANSIENT = ("connection", "socket")

# Seconds to wait before re-dispatching after a fault (indexed by retry
# number, clamped to the last entry). The dev tunnel needs ~90 s to settle
# after killing a dispatch; tests monkeypatch this to () for speed.
_RETRY_BACKOFF_S = (5.0, 30.0, 90.0)


def _is_transient_fault(e: Exception) -> bool:
    s = str(e)
    sl = s.lower()
    return (any(m in s for m in _GRPC_TRANSIENT)
            or any(m in sl for m in _PROSE_TRANSIENT))


def run_with_fault_retry(config: SVMConfig, checkpoint_path, resume,
                         attempt_fn):
    """Bounded automatic fault recovery around a whole solve attempt
    (SURVEY.md section 5.3 — the reference loses everything on a rank
    death; here a transient device-runtime fault costs at most the work
    since the last checkpoint).

    ``attempt_fn(cfg, resume, k)`` runs attempt ``k`` and returns a
    SolveResult. On a transient JaxRuntimeError with retries left, the
    compiled-program caches are cleared (a faulted dispatch can leave a
    poisoned cached executable — re-dispatching it faults instantly), the
    retry waits out the runtime's settle time, and the next attempt runs
    with ``chunk_iters`` bumped by k — a static-arg change that forces a
    genuinely fresh compile even through server-side compile caches — and
    ``resume=True`` when a checkpoint path exists (else the attempt
    restarts from the caller's initial state).
    """
    import os as _os
    import sys as _sys

    attempts = max(1, int(config.retry_faults) + 1)
    # A retry may resume ONLY from a checkpoint THIS run wrote (or one
    # the caller explicitly asked to resume from): a stale file from an
    # earlier run with matching hyperparameters would otherwise silently
    # replace the fresh training the caller asked for. Detected by mtime:
    # unchanged since before attempt 0 => not ours.
    def _mtime():
        try:
            return _os.path.getmtime(checkpoint_path) if checkpoint_path \
                else None
        except OSError:
            return None

    baseline_mtime = _mtime()

    def _resume_now():
        return resume or (bool(checkpoint_path)
                          and _mtime() is not None
                          and _mtime() != baseline_mtime)

    for k in range(attempts):
        # Retry attempts perturb tau by ~1e-6 relative: tau is a STATIC
        # argument / closure constant in EVERY engine's compiled executor
        # (per-pair, block, mesh), so this forces a genuinely fresh
        # compile even through server-side compile caches — a faulted
        # dispatch can leave a poisoned cached executable that refaults
        # instantly on re-dispatch. Numerically inert (tau is the eta
        # clamp floor, ~1e-12). chunk_iters+k additionally re-chunks the
        # per-pair observed path.
        cfg_k = config if k == 0 else config.replace(
            chunk_iters=config.chunk_iters + k,
            tau=config.tau * (1.0 + k * 1e-6))
        res_k = resume if k == 0 else _resume_now()
        try:
            return attempt_fn(cfg_k, res_k, k)
        except jax.errors.JaxRuntimeError as e:
            if k == attempts - 1 or not _is_transient_fault(e):
                # Queued cross-attempt events will never be drained
                # now — clear them so they cannot leak into the run
                # log of an unrelated later solve on this thread.
                clear_pending_obs_events()
                raise
            nxt = "from checkpoint" if _resume_now() else "from scratch"
            print(f"[fault-retry] transient device fault "
                  f"({str(e)[:160]!r}); retry {k + 1}/{attempts - 1} {nxt}",
                  file=_sys.stderr, flush=True)
            # Runlog trail (ISSUE 13 obs satellite): the faulted
            # attempt's run log died with the exception (RunObs.__del__
            # finishes it aborted=True); the NEXT attempt's run drains
            # these into `fault`/`retry` event records, so the retry
            # story is readable from the runlog alone.
            queue_pending_obs_event("fault", error=str(e)[:200],
                                    attempt=k, transient=True)
            queue_pending_obs_event("retry", attempt=k + 1,
                                    resume=bool(_resume_now()))
            jax.clear_caches()
            if _RETRY_BACKOFF_S:
                time.sleep(_RETRY_BACKOFF_S[min(k, len(_RETRY_BACKOFF_S) - 1)])
        except BaseException:
            # Any other terminal failure (NonFiniteTrajectory on a
            # safe config, validation errors, ...) exits this wrapper
            # too: same stale-event hygiene.
            clear_pending_obs_events()
            raise
    raise AssertionError("unreachable")


class NonFiniteTrajectory(FloatingPointError):
    """The chunk-boundary host observation read a non-finite optimality
    gap — the carried gradient has blown up (bf16 storage at hostile
    coefficient scale, inf features, absurd gamma/C). Raised by
    :func:`check_obs_finite` INSTEAD of letting the loop continue: NaN
    comparisons are False, so ``b_lo > b_hi + 2*eps`` would read
    "converged" and return a silently corrupt model. solve() catches
    this once and demotes to the safe configuration
    (solver/block.py demote_to_safe), restoring the last checkpoint
    when one exists."""


def check_obs_finite(b_hi: float, b_lo: float, it: int,
                     backend: str) -> None:
    """Free non-finite sentinel on the chunk-boundary observation
    (``b_hi``/``b_lo`` are already materialized host scalars).

    NaN in either extremum is corruption. For infinities, only the
    IMPOSSIBLE signs trip it: ops/select.py computes b_hi = min f over
    I_up (masked entries +inf) and b_lo = max f over I_low (masked
    -inf), so a legitimately EMPTY side reads b_hi=+inf / b_lo=-inf
    (and the stopping test correctly reads converged) — but b_hi=-inf
    or b_lo=+inf can only come from inf entries in f winning the
    min/max, and would otherwise hold the gap open forever."""
    if (b_hi != b_hi or b_lo != b_lo  # NaN
            or b_hi == float("-inf") or b_lo == float("inf")):
        raise NonFiniteTrajectory(
            f"[{backend}] non-finite optimality extrema at iteration "
            f"{it}: b_hi={b_hi!r} b_lo={b_lo!r} — the carried gradient "
            "has blown up; demoting to the safe configuration (f32 "
            "storage, stock engine) or failing loudly")


# Cross-attempt obs handoff: a faulted/demoted attempt's run log is
# already finished (aborted) when the decision to retry/demote is
# made, so the wrapper queues the event here and the NEXT attempt's
# impl drains it into its own run log right after run_obs(). Thread-
# local: concurrent solves (serving admin threads, tests) must not
# cross-pollinate each other's retry stories.
import threading as _threading  # noqa: E402  (module-scope by design)

_PENDING_OBS = _threading.local()


def queue_pending_obs_event(name: str, **fields) -> None:
    lst = getattr(_PENDING_OBS, "events", None)
    if lst is None:
        lst = _PENDING_OBS.events = []
    lst.append((name, fields))


def clear_pending_obs_events() -> None:
    _PENDING_OBS.events = []


def drain_pending_obs_events(obs) -> None:
    """Emit (and clear) queued cross-attempt events into a live run's
    log. Clears even when obs is off — stale events must never leak
    into an unrelated later solve."""
    lst = getattr(_PENDING_OBS, "events", None)
    if not lst:
        return
    _PENDING_OBS.events = []
    for name, fields in lst:
        obs.event(name, **fields)


def _solve_with_degradation(config: SVMConfig, checkpoint_path,
                            resume, run):
    """Graceful degradation around a whole solve (ISSUE 13): on a
    :class:`NonFiniteTrajectory` — the non-finite sentinel tripping at
    a chunk boundary — restore the last checkpoint this run wrote (or
    restart) and demote ONCE to the safe configuration (f32 storage,
    stock block engine; solver/block.py demote_to_safe), with a loud
    warning, ``stats['demoted_faults']`` and a ``demotion`` runlog
    event — the shard-local endgame-demotion pattern applied to
    numerics faults. A config that is ALREADY safe propagates the
    error: that is a real numerics bug (inf features, absurd gamma/C),
    and hiding it behind a retry would loop forever.

    ``run(cfg, resume)`` executes the full retry-wrapped solve under
    ``cfg``."""
    import os as _os

    def _mtime():
        try:
            return _os.path.getmtime(checkpoint_path) if checkpoint_path \
                else None
        except OSError:
            return None

    baseline_mtime = _mtime()
    try:
        return run(config, resume)
    except NonFiniteTrajectory as e:
        from dpsvm_tpu.solver.block import demote_to_safe

        safe_cfg, dropped = demote_to_safe(config)
        if safe_cfg is None:
            raise
        # Resume only a checkpoint THIS run wrote (or one the caller
        # explicitly asked for) — the run_with_fault_retry staleness
        # discipline.
        res_now = resume or (bool(checkpoint_path)
                             and _mtime() is not None
                             and _mtime() != baseline_mtime)
        import warnings

        warnings.warn(
            f"non-finite solver trajectory ({e}); DEMOTING to the safe "
            f"configuration (dropped: {', '.join(dropped)}) and "
            + ("resuming from the last checkpoint"
               if res_now else "restarting from scratch")
            + " — results will be exact but slower; investigate the "
            "input scaling / C / gamma that produced the blow-up",
            stacklevel=3)
        queue_pending_obs_event("demotion", reason=str(e)[:200],
                                dropped=list(dropped),
                                resumed=bool(res_now))
        try:
            res = run(safe_cfg, res_now)
        except BaseException:
            clear_pending_obs_events()  # stale-event hygiene
            raise
        res.stats["demoted_faults"] = \
            int(res.stats.get("demoted_faults", 0)) + 1
        res.stats["demotion"] = {"dropped": list(dropped),
                                 "resumed": bool(res_now),
                                 "reason": str(e)[:200]}
        return res


# Auto resident-Gram gating (config.gram_resident=None): fraction of the
# device's reported memory budget the (n, n) float32 Gram may occupy, and
# the n below which the build/compile overhead is not worth switching
# paths for.
_GRAM_BUDGET_FRACTION = 0.70
_GRAM_MIN_N = 8192

def _host_fingerprint(a) -> tuple:
    """Cheap content guard for the host-array memos (_XDEV_MEMO /
    _GRAM_MEMO): the buffer address (ctypes.data) plus a 256-point
    strided sample of raw values. The memos key on OBJECT IDENTITY, but
    identity alone cannot see in-place mutation — `x *= s` keeps
    `x is x` true while the resident device copy goes stale, and the
    solver would silently train on old data (ADVICE round-5, medium).

    Deliberately PROBABILISTIC: O(1) strided reads, no O(n) hash (a full
    hash of a 188 MB X per solve would cost more than the transfer it
    guards). Whole-array and regional rewrites — the observed mutation
    patterns (rescaling, renormalizing, reloading into the same buffer)
    — always hit sampled points; a sparse edit touching fewer than
    size/256 contiguous elements can slip between samples, so callers
    that surgically poke single rows should pass a fresh array (or
    np.array-copy) instead of relying on the guard."""
    import numpy as np

    arr = np.asarray(a)
    try:
        addr = arr.ctypes.data
    except (AttributeError, TypeError):
        addr = None
    if arr.size == 0:
        return (addr, arr.shape, b"")
    idx = np.linspace(0, arr.size - 1, num=min(256, arr.size),
                      dtype=np.int64)
    return (addr, arr.shape, arr.flat[idx].tobytes())


def _memo_insert(memo: dict, key, x_host, payload: tuple) -> None:
    """Install a size-1 memo entry with a SAFE weakref finalizer: the
    eviction callback pops the key only while it still maps to THIS
    entry. The naive `pop(key)` finalizer had a lifetime bug (ADVICE
    round-5, low): replace the entry for the same key with a new host
    array, then let the OLD array die — its finalizer would evict the
    NEW, live entry (for _GRAM_MEMO, a multi-GB resident Gram rebuilt on
    the next leg for nothing).

    The entry is matched by a SENTINEL TOKEN stored inside it, not by
    entry identity: a closure holding the entry itself would form a
    reference cycle (entry -> weakref -> callback -> entry) that keeps
    an evicted multi-GB device Gram alive until the cyclic GC runs —
    refcount-immediate release on memo.clear() is the property the
    size-1 discipline exists for. Entry layout:
    (weakref, token, *payload, fingerprint)."""
    import weakref

    memo.clear()  # size-1 discipline: never hold two entries
    token = object()

    def _evict(_r, _memo=memo, _key=key, _token=token):
        ent = _memo.get(_key)
        if ent is not None and ent[1] is _token:
            _memo.pop(_key, None)

    try:
        ref = weakref.ref(x_host, _evict)
    except TypeError:
        return  # non-weakrefable host container: just skip the memo
    memo[key] = (ref, token, *payload, _host_fingerprint(x_host))


# Size-1 memo: (key) -> (weakref-to-host-x, device Gram). Reconstruction
# legs (solver/reconstruct.py) call solve() once per leg with the SAME
# host array; rebuilding a ~10 GB Gram every leg would cost ~12 s of HBM
# writes each. Keyed by object identity (guarded by the weakref so a
# recycled id can never alias) plus everything that changes the values.
_GRAM_MEMO: dict = {}

# Size-1 memo for the (x_dev, x_sq) device pair, same identity+weakref
# discipline as _GRAM_MEMO. One-vs-rest multiclass training calls
# solve() once per class on the SAME host X (188 MB at the MNIST shape);
# without this every class re-pays the host->device transfer and the
# squared-norm pass (VERDICT round-4 item 2). Reconstruction legs hit it
# too. k_diag is NOT memoized (it depends on kp and costs one tiny
# elementwise dispatch).
_XDEV_MEMO: dict = {}


def _device_x_cached(x_host, build_x_p, n_pad, dtype, device):
    """(x_dev, x_sq) for feature-kernel solves. `build_x_p` is called
    only on a miss (it materializes the padded host copy). A hit needs
    identity AND an unchanged content fingerprint (_host_fingerprint):
    in-place mutation of a reused host array must rebuild, not silently
    train on the stale device copy."""
    d = x_host.shape[1]
    key = ((n_pad, d), str(dtype), getattr(device, "id", None))
    ent = _XDEV_MEMO.get(key)
    if ent is not None and ent[0]() is x_host \
            and ent[-1] == _host_fingerprint(x_host):
        return ent[2], ent[3]  # (ref, token, x_dev, x_sq, fp)
    x_dev = jax.device_put(jnp.asarray(build_x_p(), dtype), device)
    x_sq = jax.jit(squared_norms)(x_dev)
    _memo_insert(_XDEV_MEMO, key, x_host, (x_dev, x_sq))
    return x_dev, x_sq


# HBM per chip by TPU generation, for backends that do not report
# bytes_limit (the tunneled axon runtime returns None). Matched against
# device_kind substrings; unknown TPU kinds fall back to 16 GiB (every
# generation since v3).
_TPU_HBM_GIB = (("v5 lite", 16), ("v5e", 16), ("v5p", 95), ("v4", 32),
                ("v6", 32), ("v3", 16), ("v2", 8))


def _gram_budget_bytes(device) -> int:
    try:
        stats = device.memory_stats() or {}
        limit = int(stats.get("bytes_limit", 0))
        if limit > 0:
            return int(_GRAM_BUDGET_FRACTION * limit)
    except Exception:
        pass
    if getattr(device, "platform", None) == "tpu":
        kind = getattr(device, "device_kind", "").lower()
        gib = next((g for k, g in _TPU_HBM_GIB if k in kind), 16)
        return int(_GRAM_BUDGET_FRACTION * gib * (1 << 30))
    return 0  # unknown budget (e.g. CPU backends): auto stays off


def _resolve_gram(config: SVMConfig, kp: KernelParams, n: int,
                  device) -> bool:
    """Whether this solve runs in resident-Gram mode (see config)."""
    if kp.kind == "precomputed" or config.engine == "pallas":
        return False
    if config.gram_resident is not None:
        return bool(config.gram_resident)
    return (config.engine == "xla" and n >= _GRAM_MIN_N
            and 4 * n * n <= _gram_budget_bytes(device))


def _resident_gram_cached(x_host, build_x_p, n_pad, dtype,
                          kp: KernelParams, config: SVMConfig, device):
    """(gram, k_diag) for resident-Gram mode, memoized across legs.

    Owns the whole build so a memo HIT costs nothing: `build_x_p` is
    only called on a miss (the padded host copy is itself ~O(n d)
    bytes), and no feature re-upload or squared-norm/diag recompute
    happens. A weakref finalizer evicts the entry the moment the host
    array dies — a multi-GB device Gram must never outlive the data it
    was built from (it would pin up to ~70% of HBM against later
    unrelated work). A hit needs identity AND an unchanged content
    fingerprint (_host_fingerprint, the in-place-mutation guard)."""
    from dpsvm_tpu.ops.kernels import resident_gram

    # Keyed on the PADDED build shape, not the host shape: the same host
    # X solved at two pad_to buckets needs two distinct Grams — and on
    # the EFFECTIVE storage dtype, not config.dtype: a bf16_gram solve
    # whose gate accepted builds from bfloat16-rounded features while
    # its config still says 'float32', and must never share a Gram with
    # a plain float32 solve on the same host array (the _device_x_cached
    # discipline).
    key = (kp, (n_pad, x_host.shape[1]), str(dtype),
           getattr(device, "id", None), config.resolve_precision())
    ent = _GRAM_MEMO.get(key)
    if ent is not None and ent[0]() is x_host \
            and ent[-1] == _host_fingerprint(x_host):
        return ent[2], ent[3]  # (ref, token, g, k_diag, fp)
    x_feat = jax.device_put(jnp.asarray(build_x_p(), dtype), device)
    x_sq_f = jax.jit(squared_norms)(x_feat)
    k_diag = jax.jit(kernel_diag, static_argnames="params")(x_sq_f,
                                                            params=kp)
    g = resident_gram(x_feat, x_sq_f, kp)
    # Synchronize BEFORE the caller dispatches the solve executor: the
    # build transiently holds a second O(n^2) working buffer, and letting
    # the executor's allocations overlap it OOMs exactly at the largest
    # shapes this mode exists for (measured: n=50k fails async, passes
    # synced, on a 16 GiB v5e).
    jax.block_until_ready(g)
    _memo_insert(_GRAM_MEMO, key, x_host, (g, k_diag))
    return g, k_diag


def solve(
    x,
    y,
    config: SVMConfig,
    callback=None,
    device: Optional[jax.Device] = None,
    checkpoint_path: Optional[str] = None,
    resume: bool = False,
    alpha_init=None,
    f_init=None,
    pad_to: Optional[int] = None,
    warm_start=None,
) -> SolveResult:
    """Train binary C-SVC on one chip. Returns SolveResult.

    `pad_to` (shape bucketing): pad the row dimension to at least this
    many rows, masking the padding out of every selection. Callers with
    MANY distinct problem sizes (one-vs-one multiclass trains k(k-1)/2
    subset shapes) round sizes up to a few buckets so each bucket
    compiles ONCE — XLA executors are shape-keyed, and a fresh compile
    per shape costs more than the padded rows' dead lanes. Results
    (alpha, f, SV counts) cover only the real rows.

    `callback(iter, b_hi, b_lo, state)`, when given, fires once per chunk —
    the structured-progress hook the reference lacks (its per-iteration
    print is commented out, svmTrainMain.cpp:237-239). ABORT CONTRACT: a
    truthy return value stops the solve cleanly at that chunk boundary
    (state is kept, a due checkpoint is forced); return None/False/0 —
    not, say, the gap — from callbacks that only observe. DONATION
    CAVEAT: the state a callback receives is DONATED to the next
    chunk's dispatch — read scalars/arrays inside the call (or copy
    with `np.asarray`), but do not retain the state object itself;
    its buffers are dead once the solve proceeds.

    With `checkpoint_path` and config.checkpoint_every > 0, solver state
    (alpha, f, iteration) is persisted periodically; `resume=True` restarts
    from the file if present (a capability gap in the reference — SURVEY.md
    section 5.3: an MPI rank death loses the whole run).

    `alpha_init` / `f_init` override the C-SVC start point (alpha = 0,
    f = -y). They express other SMO-reducible problems through the same
    engine: the general dual min 1/2 a^T Q a + p^T a with y in {+-1} and
    Q_ij = y_i y_j K_ij starts from f = y * (Q alpha_init + p) — epsilon-SVR
    uses the 2n-variable expansion with f_init = [eps - z; -eps - z]
    (models/svr.py), one-class SVM a nonzero alpha_init (models/oneclass.py).
    A checkpoint resume, when present, takes precedence over both.

    `warm_start` (ISSUE 18) is the high-level seed: a
    solver.warmstart.WarmStart carry (a prior model's SVs or a raw
    alpha vector) that is feasibility-repaired into THIS config's box/
    equality constraints and whose gradient is rebuilt in one streamed
    pass over X before delegating to the alpha_init/f_init plumbing. A
    seed that repairs to all-zeros routes bit-identically through the
    cold path (prepare_warm_start returns None). Mutually exclusive
    with alpha_init/f_init.
    """
    import numpy as np

    if warm_start is not None:
        if alpha_init is not None or f_init is not None:
            raise ValueError(
                "pass either warm_start or alpha_init/f_init, not both")
        from dpsvm_tpu.solver.warmstart import prepare_warm_start

        a0, f0, wstats = prepare_warm_start(x, y, config, warm_start,
                                            device=device)
        res = solve(x, y, config, callback=callback, device=device,
                    checkpoint_path=checkpoint_path, resume=resume,
                    alpha_init=a0, f_init=f0, pad_to=pad_to)
        res.stats["warm_start"] = wstats
        return res
    if config.selection == "nu" and alpha_init is None:
        # The nu rule pairs within one class; from the C-SVC zero start no
        # class has both an I_up and an I_low member, so the gap reads
        # closed at iteration 0 and a garbage model would return as
        # "converged". Only the nu trainers provide the feasible start.
        raise ValueError(
            "selection='nu' is internal to the nu duals — call "
            "train_nusvc/train_nusvr (models/nusvm.py) instead")
    if config.ooc:
        # Out-of-core streaming driver (solver/ooc.py): X stays in host
        # memory; the block engine's fold streams over double-buffered
        # tiles. Its own host loop (the stream must be fed per round),
        # same result contract — including checkpoint/resume (v2
        # full-carry checkpoints, bitwise cache-off resume) and the
        # non-finite demotion wrapper below.
        from dpsvm_tpu.solver.ooc import solve_ooc

        return _solve_with_degradation(
            config, checkpoint_path, resume,
            lambda cfg, res: solve_ooc(
                x, y, cfg, callback=callback, device=device,
                checkpoint_path=checkpoint_path, resume=res,
                alpha_init=alpha_init, f_init=f_init, pad_to=pad_to))
    if config.reconstruct_every:
        # Exact-f64 reconstruction legs around the device solve: the
        # productized form of the extreme-C recipe (solver/reconstruct.py;
        # convergence is judged on the RECONSTRUCTED gap, matching the
        # reference's in-tool stopping rule svmTrainMain.cpp:310 at
        # hyperparameters where fp32 carried gradients cannot be trusted).
        from dpsvm_tpu.solver.reconstruct import solve_in_legs

        return solve_in_legs(solve, x, y, config, callback=callback,
                             checkpoint_path=checkpoint_path, resume=resume,
                             alpha_init=alpha_init, f_init=f_init,
                             device=device)

    def run(cfg, res):
        def attempt(cfg_k, res_k, k):
            return _solve_impl(x, y, cfg_k,
                               _retry_callback(callback, cfg_k,
                                               checkpoint_path, k),
                               device, checkpoint_path, res_k,
                               alpha_init, f_init, pad_to)

        with _precision_ctx(cfg):
            return run_with_fault_retry(cfg, checkpoint_path, res,
                                        attempt)

    return _solve_with_degradation(config, checkpoint_path, resume, run)


def _noop_callback(it, b_hi, b_lo, state):
    """Observation-forcing callback used by fault retries (chunked
    dispatches instead of one long one). Returns None: never aborts."""
    return None


def _retry_callback(callback, cfg_k, checkpoint_path, k):
    """The callback a retry attempt should run with: unchanged on attempt
    0 or when anything already observes chunk boundaries; otherwise the
    no-op observer, so retries dispatch in chunks instead of re-running
    the single long dispatch the degraded runtime just killed. The
    condition mirrors _solve_impl's `observe` predicate (a checkpoint
    cadence without a path observes nothing). Shared by solve() and
    solve_mesh()."""
    if k > 0 and callback is None and not cfg_k.verbose \
            and not cfg_k.check_numerics \
            and not (cfg_k.checkpoint_every and checkpoint_path):
        return _noop_callback
    return callback


def _solve_impl(x, y, config, callback, device, checkpoint_path, resume,
                alpha_init, f_init, pad_to=None) -> SolveResult:
    import numpy as np

    t_entry = time.perf_counter()  # phase clock: setup starts here
    x = np.asarray(x, np.float32)
    y_np = np.asarray(y, np.int32)
    n, d = x.shape
    gamma = config.resolve_gamma(d)
    kp = KernelParams(config.kernel, gamma, config.degree, config.coef0)
    dtype = jnp.bfloat16 if config.dtype == "bfloat16" else jnp.float32
    if config.dtype == "bfloat16":
        from dpsvm_tpu.ops.kernels import warn_if_bf16_degrades
        warn_if_bf16_degrades(x, config)
    # bf16 Gram path (config.bf16_gram): flip X storage to bfloat16
    # (f32 MXU accumulation) ONLY where the per-problem perturbation
    # bound allows; a refusal stays float32 and is loud in stats + a
    # warning (ops/kernels.py resolve_bf16_gram).
    bf16_gram_stats = {}
    if config.bf16_gram:
        from dpsvm_tpu.ops.kernels import resolve_bf16_gram

        _bfg_on, _, _bfg_entry = resolve_bf16_gram(x, config, gamma)
        bf16_gram_stats = {"bf16_gram": _bfg_entry}
        if _bfg_on:
            dtype = jnp.bfloat16
        else:
            import warnings

            warnings.warn(_bfg_entry["note"], stacklevel=3)

    if device is None:
        device = jax.devices()[0]
    use_pallas = config.engine == "pallas"
    use_block = config.engine == "block"
    # The Gram is built at the PADDED size — budget-gate on that.
    use_gram = _resolve_gram(config, kp, max(n, int(pad_to or 0)), device)
    # Fused fold+select (ops/pallas_fold_select.py): auto on real TPUs
    # for the 2-sided selection rules; needs >= q/2 128-element rows so
    # every working-set slot can find a candidate.
    # The fused path's hard constraint is on the PADDED row count (the
    # top-h runs over n_pad/128 per-row candidates): q/2 <= n_pad/128.
    # Auto mode additionally requires large n: the fuse removes the
    # full-n mask+approx_max_k stage but adds a pallas launch + delta
    # round-trip + candidate top-k — a net LOSS on small rounds. The
    # crossover is d-dependent and pinned by the round-5 sweep
    # (solver/block.py fused_fold_pays docstring table).
    from dpsvm_tpu.solver.block import (autotune_gate_resolver,
                                        fused_fold_pays, fused_round_pays,
                                        pipeline_pays)

    # Auto-gate resolution (ISSUE 14): each None-valued accelerator
    # knob resolves through the installed DeviceProfile for THIS
    # device kind (dpsvm_tpu/autotune — measured verdicts) with the
    # hand-measured *_pays expressions as the no-profile default.
    # Provenance of every gate actually consulted lands in
    # stats["autotune"] and the runlog manifest via _autotune_embed.
    _auto_gate, _autotune_embed = autotune_gate_resolver(device)

    n_pad_fused = -(-n // 1024) * 1024
    # Pipelined rounds (config.pipeline_rounds; solver/block.py
    # run_chunk_block_pipelined): next-round selection/gather/Gram issued
    # from the pre-fold carry, overlappable with the subproblem chain.
    # Supersedes the fused fold+select when both would apply (the
    # prefetch removes the selection from the round's critical path
    # entirely; fusing it into the fold would re-serialize it). Works
    # with precomputed kernels and the resident Gram (the prefetch's
    # Gram block is a column gather there).
    # The auto gate must never override an EXPLICIT fused_round=True
    # (config rejects the explicit pipeline+fusedround pair as "one or
    # the other"; the forced knob wins over pipeline_pays the same way).
    use_pipe = (use_block and config.selection != "nu"
                and not config.active_set_size
                and (config.pipeline_rounds
                     if config.pipeline_rounds is not None
                     else (not config.fused_round
                           and _auto_gate(
                               "pipeline_rounds",
                               device.platform == "tpu"
                               and pipeline_pays(n, d)))))
    # The prefetch's own selection pass: the one-pass Pallas candidate
    # kernel where the fused path's padding contract holds on a real
    # TPU, else the plain masked top-k (CPU tests keep the jnp path —
    # interpret-mode Pallas inside every round would crawl; the kernel
    # itself is unit-tested in interpret mode).
    pipe_pallas_select = (use_pipe and kp.kind != "precomputed"
                          and not use_gram
                          and device.platform == "tpu"
                          and min(config.working_set_size, n_pad_fused)
                          <= n_pad_fused // 64)
    # One-HBM-pass fused round (config.fused_round; ops/pallas_round.py
    # + solver/block.py run_chunk_block_fusedround): the fused-fold
    # engine with the remaining XLA round stages (gather, Gram, kernel
    # rows, fold contraction) fused into two Pallas passes. Same padding
    # contract and restrictions as the fused fold+select; supersedes
    # fused_fold when both would engage (it strictly extends that
    # kernel's fusion); pipeline_rounds=True rejects it in config.
    use_fusedround = (use_block and not use_pipe
                      and config.selection != "nu"
                      and not config.active_set_size
                      and kp.kind != "precomputed" and not use_gram
                      and min(config.working_set_size, n_pad_fused)
                      <= n_pad_fused // 64
                      and (config.fused_round
                           if config.fused_round is not None
                           else _auto_gate(
                               "fused_round",
                               device.platform == "tpu"
                               and fused_round_pays(n_pad_fused, d))))
    use_fused = (use_block and not use_pipe and not use_fusedround
                 and config.selection != "nu"
                 and not config.active_set_size
                 and kp.kind != "precomputed" and not use_gram
                 and min(config.working_set_size, n_pad_fused)
                 <= n_pad_fused // 64
                 and (config.fused_fold if config.fused_fold is not None
                      else (device.platform == "tpu"
                            and fused_fold_pays(n_pad_fused, d))))
    block_rows = 64
    # Engine row-granularity, then the caller's shape bucket (`pad_to`,
    # see solve()): padded rows are masked out of every selection.
    n_min = max(n, min(pad_to, 2 ** 31) if pad_to else n)
    if use_pallas:
        # Pad rows to a whole number of (block_rows, 128) kernel blocks;
        # padding is masked out of selection via `valid`.
        blk = block_rows * 128
        n_pad = -(-n_min // blk) * blk
    elif use_fused or use_fusedround or pipe_pallas_select:
        blk = 8 * 128  # fold_select's (block_rows=8, 128) grid blocks
        n_pad = -(-n_min // blk) * blk
    else:
        n_pad = n_min

    if kp.kind == "precomputed" and x.shape[0] != x.shape[1]:
        # Checked before any device transfer or compute is spent.
        raise ValueError(
            f"kernel='precomputed' needs the square (n, n) Gram "
            f"matrix as x; got {x.shape}")
    if kp.kind == "precomputed" and n_pad != n:
        raise ValueError(
            "pad_to does not compose with kernel='precomputed' (the "
            "padded Gram rows/columns would need kernel values)")

    def build_x_p():
        if n_pad == n:
            return x
        xp = np.zeros((n_pad, d), np.float32)
        xp[:n] = x
        return xp

    if n_pad == n:
        y_p = y_np.astype(np.float32)
    else:
        y_p = np.ones((n_pad,), np.float32)
        y_p[:n] = y_np
    y_dev = jax.device_put(jnp.asarray(y_p, jnp.float32), device)
    if n_pad == n and not (use_pallas or use_fused or use_fusedround
                           or pipe_pallas_select):
        valid_dev = None
    else:
        valid_np = np.zeros((n_pad,), bool)
        valid_np[:n] = True
        valid_dev = jax.device_put(jnp.asarray(valid_np), device)
    if use_gram:
        # Resident-Gram mode (config.gram_resident): materialize the
        # (n, n) kernel matrix on device once and run the solve through
        # the precomputed-kernel branches — per-pair kernel rows become
        # row gathers. n_pad == n here (the gram engines never pad), the
        # kernel diag comes from the FEATURE side (exact: rbf diag is
        # exactly 1, no Gram round-trip), and the original host x stays
        # the memo key so reconstruction legs reuse one build.
        x_dev, k_diag = _resident_gram_cached(x, build_x_p, n_pad, dtype,
                                              kp, config, device)
        kp = KernelParams("precomputed")
        x_sq = jnp.zeros((n_pad,), jnp.float32)
    elif kp.kind == "precomputed":
        # x IS the Gram matrix: its diagonal is the kernel diag, and
        # the squared-norm pass (an O(n^2) read no precomputed branch
        # ever consumes) is replaced by a zero placeholder.
        x_dev = jax.device_put(jnp.asarray(build_x_p(), dtype), device)
        x_sq = jnp.zeros((n_pad,), jnp.float32)
        k_diag = jnp.diagonal(x_dev).astype(jnp.float32)
    else:
        # Identity-memoized: repeated solves on the same host X (OvR
        # multiclass, reconstruction legs) pay the transfer and the
        # squared-norm pass once.
        x_dev, x_sq = _device_x_cached(x, build_x_p, n_pad, dtype, device)
        k_diag = jax.jit(kernel_diag,
                         static_argnames="params")(x_sq, params=kp)

    from dpsvm_tpu.utils.checkpoint import PeriodicCheckpointer, resume_solver_state

    cache_lines = min(config.cache_lines, n_pad)
    # The block engine has no LRU cache (its working-set block is the
    # reuse mechanism) — don't allocate one or report cache stats for it.
    # Resident-Gram mode supersedes the cache entirely (every row is
    # already resident), so a configured cache is silently idle there.
    use_micro = (config.engine == "xla" and config.pair_batch > 1)
    use_cache = (cache_lines > 0 and not use_block and not use_gram
                 and not use_micro)
    state = init_state(n_pad, y_dev, cache_lines if use_cache else 1)
    if alpha_init is not None:
        a_p = np.zeros((n_pad,), np.float32)
        a_p[:n] = np.asarray(alpha_init, np.float32)
        state = state._replace(alpha=jax.device_put(jnp.asarray(a_p), device))
    if f_init is not None:
        f_p = np.asarray(-y_p, np.float32)
        f_p[:n] = np.asarray(f_init, np.float32)
        state = state._replace(f=jax.device_put(jnp.asarray(f_p), device))
    if resume:
        restored = resume_solver_state(checkpoint_path, config, n)
        if restored is not None:
            a0, f0, it0, bh0, bl0 = restored
            a_pad = np.zeros((n_pad,), np.float32)
            a_pad[:n] = a0
            f_pad = np.asarray(-y_p, np.float32)
            f_pad[:n] = f0
            state = state._replace(
                alpha=jnp.asarray(a_pad), f=jnp.asarray(f_pad),
                b_hi=jnp.float32(bh0), b_lo=jnp.float32(bl0),
                it=jnp.int32(it0))
    if config.active_set_size:
        # Measured across every regime tried over two rounds (extreme-C
        # stress, moderate-C huge-n, sparse-margin blobs; 12 configs —
        # BENCH_COVTYPE_SWEEP.md round-5 section), active-set shrinking
        # NEVER beat the plain block engine on TPU: the plain engine's
        # full-n fold is one fused MXU matmul whose cost the active
        # gather/reconcile machinery does not undercut, and restricted
        # cycles converge slower. The knob stays (it is exact, and other
        # hardware may differ) but using it warrants this warning.
        import warnings

        warnings.warn(
            "active_set_size (shrinking) is measured SLOWER than the "
            "plain block engine in every regime tried on TPU (best case "
            "a tie; see BENCH_COVTYPE_SWEEP.md) — prefer "
            "active_set_size=0 unless you have measured a win on your "
            "workload", stacklevel=2)
    if use_block:
        from dpsvm_tpu.solver.block import (BlockState,
                                            run_chunk_block_donated)

        # Clamp the block height to the dataset (top_k k <= n), kept even
        # so the up/low halves stay balanced (multiple of 4 for the nu
        # rule's per-class quarters).
        gran = 4 if config.selection == "nu" else 2
        q = max(gran, min(config.working_set_size, n_pad))
        q -= q % gran
        inner = config.inner_iters or 2 * q
        # Active-set shrinking: clamp m into [q, n] on the same class
        # granularity. (Even m == n is not quite the plain engine: each
        # selection side still gets only m/2 slots, so one class's
        # low-rank violators can sit out a cycle — still exact, just a
        # different, restricted round sequence.)
        m_act = 0
        if config.active_set_size:
            m_act = max(q, min(config.active_set_size, n_pad))
            m_act -= m_act % gran
        state = BlockState(alpha=state.alpha, f=state.f, b_hi=state.b_hi,
                           b_lo=state.b_lo, pairs=state.it,
                           rounds=jnp.int32(0))
    if config.compensated:
        state = state._replace(f_err=jnp.zeros_like(state.f))

    state = jax.device_put(state, device)
    max_iter = jnp.int32(config.max_iter)
    # budget_mode: compile the stopping test with _BUDGET_EPS so the loop
    # runs to the exact max_iter pair budget; the returned `converged` is
    # re-derived from the final state at the real epsilon below.
    eps_run = _BUDGET_EPS if config.budget_mode else float(config.epsilon)
    start_iter = int(state.pairs if use_block else state.it)
    ckpt = PeriodicCheckpointer(checkpoint_path, config, start_iter)
    # Pallas kernels lower for the device the solve actually targets, not
    # whatever the platform default happens to be.
    interpret = device.platform != "tpu"
    if callback is not None and hasattr(callback, "on_start"):
        callback.on_start(start_iter)

    # With nothing to observe between chunks, run the entire solve in one
    # dispatch (the sentinel chunk never splits the while_loop) and pull
    # ONE packed scalar triple at the end — device->host latency dominates
    # chunk cadence on tunneled runtimes.
    observe = (callback is not None or config.verbose
               or config.check_numerics or ckpt.active)
    chunk_len = int(config.chunk_iters) if observe else _UNOBSERVED_CHUNK
    if use_block:
        rounds_per_chunk = (max(1, chunk_len // inner)
                            if observe else _UNOBSERVED_CHUNK)

    # Observability (dpsvm_tpu/obs; NULL_OBS when disabled). Obs is NOT
    # part of `observe` above: its chunk records ride whatever cadence
    # the solve already has, so enabling it cannot change chunking,
    # dispatch counts or compiled programs.
    from dpsvm_tpu.obs import run_obs

    obs = run_obs("solve", config,
                  meta={"n": n, "d": d, "n_pad": n_pad,
                        "engine": config.engine,
                        "kernel": config.kernel,
                        "selection": config.selection,
                        "gram_resident": bool(use_gram),
                        "pipelined": bool(use_block and use_pipe),
                        "fused_fold": bool(use_block and use_fused),
                        "fused_round": bool(use_block and use_fusedround),
                        "observed_chunks": observe,
                        # Gate-resolution provenance (ISSUE 14): how
                        # each consulted auto knob resolved — profile
                        # file + probe ratio + threshold, or the
                        # hand-measured default.
                        **_autotune_embed()})
    drain_pending_obs_events(obs)

    # PHASE CLOCK (honest per-phase wall time, SolveResult.stats
    # ["phase_seconds"]). jax dispatches are async, so phase boundaries
    # are only meaningful at device sync points; the contract here is
    # ONE block_until_ready per boundary, at chunk boundaries only:
    #   setup    -- _solve_impl entry -> all staged operands + initial
    #               state retired on device (the sync below — without
    #               it, staging time would silently ride into the
    #               first chunk's train_seconds);
    #   solve    -- sum of dispatch -> chunk-retired intervals (each
    #               bounded by the loop's existing block_until_ready —
    #               no new sync);
    #   observe  -- host work between chunks: the packed scalar pull,
    #               callbacks, checkpoint writes, verbose prints;
    #   finalize -- loop exit -> result assembly (alpha/f pulls,
    #               budget-exit extrema refresh).
    jax.block_until_ready((x_dev, x_sq, k_diag, state))
    phase_seconds = {"setup": time.perf_counter() - t_entry,
                     "solve": 0.0, "observe": 0.0, "finalize": 0.0}

    # train_seconds accumulates DEVICE time only (dispatch -> all chunk
    # work retired, bounded by block_until_ready). Host-side observation —
    # the packed scalar pull, callbacks, checkpoint writes — happens
    # between chunks with the clock stopped: on tunneled runtimes a single
    # device->host transfer costs ~80 ms, which would otherwise dwarf the
    # solve itself. The reference's timer (svmTrainMain.cpp:206-312) wraps
    # its loop the same way conceptually: its per-iteration D2H reads are
    # part of the algorithm's critical path (the host drives every
    # iteration); here the device runs the whole loop autonomously.
    train_seconds = 0.0
    dispatches = 0  # executor dispatches this host loop made (observability)
    while True:
        # Span brackets dispatch -> chunk retired; try/finally so a
        # transient device fault mid-chunk (the fault-retry path)
        # cannot leak an entered TraceAnnotation into the captured
        # device trace. Null span when obs/tracing are off.
        _sp = obs.span("solver/chunk")
        _sp.__enter__()
        try:
            t0 = time.perf_counter()
            dispatches += 1
            faults.device_fault("dispatch", f"chunk {dispatches}")
            if use_pallas:
                state = _run_chunk_pallas(
                    x_dev, y_dev, x_sq, valid_dev, state, max_iter,
                    kp, config.c_bounds(), eps_run, float(config.tau),
                    chunk_len, use_cache, block_rows, interpret)
            elif use_block and m_act:
                # Donated carries on every block variant (PR 5 pattern,
                # completed by the ISSUE 12 satellite): the loop only
                # ever reads the NEW state, so the old (n,) alpha/f
                # buffers leave the live set (tpulint pins missed=0).
                from dpsvm_tpu.solver.block import (
                    run_chunk_block_active_donated)

                state = run_chunk_block_active_donated(
                    x_dev, y_dev, x_sq, k_diag, valid_dev, state,
                    max_iter, kp, config.c_bounds(), eps_run,
                    float(config.tau), q, inner, rounds_per_chunk,
                    m_act, int(config.reconcile_rounds),
                    inner_impl="pallas" if not interpret else "xla",
                    selection=config.selection,
                    pair_batch=int(config.pair_batch))
            elif use_block and use_pipe:
                from dpsvm_tpu.solver.block import (
                    run_chunk_block_pipelined_donated)

                state = run_chunk_block_pipelined_donated(
                    x_dev, y_dev, x_sq, k_diag, valid_dev, state,
                    max_iter, kp, config.c_bounds(), eps_run,
                    float(config.tau), q, inner, rounds_per_chunk,
                    inner_impl="pallas" if not interpret else "xla",
                    interpret=interpret,
                    selection=config.selection,
                    pair_batch=int(config.pair_batch),
                    pallas_select=pipe_pallas_select)
            elif use_block and use_fusedround:
                from dpsvm_tpu.solver.block import (
                    run_chunk_block_fusedround_donated)

                state = run_chunk_block_fusedround_donated(
                    x_dev, y_dev, x_sq, k_diag, valid_dev, state,
                    max_iter, kp, config.c_bounds(), eps_run,
                    float(config.tau), q, inner, rounds_per_chunk,
                    inner_impl="pallas" if not interpret else "xla",
                    interpret=interpret,
                    selection=config.selection,
                    pair_batch=int(config.pair_batch))
            elif use_block and use_fused:
                from dpsvm_tpu.solver.block import (
                    run_chunk_block_fused_donated)

                state = run_chunk_block_fused_donated(
                    x_dev, y_dev, x_sq, k_diag, valid_dev, state,
                    max_iter, kp, config.c_bounds(), eps_run,
                    float(config.tau), q, inner, rounds_per_chunk,
                    inner_impl="pallas" if not interpret else "xla",
                    interpret=interpret,
                    selection=config.selection,
                    pair_batch=int(config.pair_batch))
            elif use_block:
                # Donated carry: the old state is dead the moment the
                # chunk is dispatched (this loop only ever reads the
                # NEW state), so its (n,) alpha/f buffers leave the
                # live set instead of doubling it (tpulint pins
                # declared_donated on this path).
                state = run_chunk_block_donated(
                    x_dev, y_dev, x_sq, k_diag, valid_dev, state,
                    max_iter, kp, config.c_bounds(), eps_run,
                    float(config.tau), q, inner, rounds_per_chunk,
                    inner_impl="pallas" if not interpret else "xla",
                    selection=config.selection,
                    pair_batch=int(config.pair_batch))
            elif use_micro:
                state = _run_chunk_micro(
                    x_dev, y_dev, x_sq, k_diag, valid_dev, state,
                    max_iter, kp, config.c_bounds(), eps_run,
                    float(config.tau), chunk_len,
                    int(config.pair_batch))
            else:
                state = _run_chunk(
                    x_dev, y_dev, x_sq, k_diag, valid_dev, state,
                    max_iter, kp, config.c_bounds(), eps_run,
                    float(config.tau), chunk_len, use_cache,
                    config.selection)
            jax.block_until_ready(state)
        finally:
            _sp.__exit__(None, None, None)
        chunk_dt = time.perf_counter() - t0
        train_seconds += chunk_dt
        t_obs0 = time.perf_counter()
        # Block-engine note: the carried extrema are computed by each
        # round's selection BEFORE its fold, so the (b_hi, b_lo) observed
        # here — callback/verbose gap, checkpointed b's — lag the pair
        # count by up to one round (<= inner_iters pairs). Harmless for
        # control flow: a stale-open gap just dispatches one more (gated)
        # chunk, a restored stale checkpoint gap is re-derived by the
        # next round's selection, and the final SolveResult refreshes
        # budget exits exactly (refresh_extrema_host below).
        it, b_hi, b_lo = _unpack_obs(_pack_obs(
            state.pairs if use_block else state.it, state.b_hi, state.b_lo))
        # Non-finite sentinel (free — the extrema are already host
        # scalars): a NaN gap would read "converged" below (NaN
        # comparisons are False) and return a silently corrupt model;
        # raise instead so _solve_with_degradation can restore the
        # checkpoint and demote to the safe configuration.
        b_hi, b_lo = faults.poison_obs(b_hi, b_lo)
        check_obs_finite(b_hi, b_lo, it, "single-chip")
        obs.chunk(pairs=it, b_hi=b_hi, b_lo=b_lo,
                  device_seconds=chunk_dt, dispatch=dispatches)
        converged = not (b_lo > b_hi + 2.0 * eps_run)
        abort = bool(callback is not None
                     and callback(it, b_hi, b_lo, state))
        if config.check_numerics:
            assert_finite_state(state, it, "single-chip")
        if ckpt.due(it) or (abort and ckpt.active):
            # The gate runs BEFORE the np.asarray materialization (hot
            # paths must not pull device arrays when nothing will be
            # written); abort exits force the save — the state being
            # stopped at must not exist only in memory.
            ckpt.save(it, np.asarray(state.alpha)[:n],
                      np.asarray(eff_f(state))[:n], b_hi, b_lo, force=True)
        if config.verbose:
            gap = b_lo - b_hi
            print(f"[smo] iter={it} b_lo-b_hi={gap:.6f} "
                  f"hits={int(state.hits)}")
        phase_seconds["observe"] += time.perf_counter() - t_obs0
        if converged or it >= config.max_iter:
            break
        if abort:
            # Clean callback-initiated stop at the chunk boundary (used
            # e.g. to stop at a measured true-gap plateau; see
            # docs/ARCHITECTURE.md round-3 findings). Checked AFTER the
            # convergence test so an abort on the closing chunk still
            # reports converged=True. No reference equivalent: its loop
            # is uninterruptible to max_iter.
            break

    t_fin0 = time.perf_counter()
    alpha = np.asarray(state.alpha)[:n]
    f_final = np.asarray(eff_f(state))[:n]
    if (use_block or config.budget_mode) and not converged:
        # Budget exits report the honest stopping rule at the REAL
        # epsilon on the final state (budget_mode runs the loop itself
        # with _BUDGET_EPS, which never closes).
        b_hi, b_lo, converged = refresh_extrema_host(
            f_final, alpha, y_np, config.c_bounds(),
            config.epsilon, rule=config.selection)
    # Hit-rate denominator covers only THIS run's lookups (post-resume).
    total_lookups = 2 * (it - start_iter) if use_cache else 0
    cache_hits = int(state.hits)
    hit_rate = (cache_hits / total_lookups) if total_lookups else 0.0
    # Evictions, derived host-side with no extra carry state: every
    # miss writes a line, a line leaves key=-1 at most once (keys never
    # return to -1), so evictions = misses - lines-filled-from-empty.
    cache_evictions = 0
    if use_cache:
        filled = int(np.count_nonzero(np.asarray(state.cache.keys) >= 0))
        cache_evictions = max(0, (total_lookups - cache_hits) - filled)
    phase_seconds["solve"] = train_seconds
    phase_seconds["finalize"] = time.perf_counter() - t_fin0
    phase_seconds = {k: round(v, 6) for k, v in phase_seconds.items()}
    stats = {
        "cache_hits": cache_hits,
        "cache_lookups": total_lookups,
        "cache_hit_rate": hit_rate,
        "cache_evictions": cache_evictions,
        "f": f_final,
        # Honest per-phase wall clock; sync discipline documented at
        # the phase-clock block above (one block_until_ready per
        # boundary, chunk boundaries only).
        "phase_seconds": phase_seconds,
        **({"outer_rounds": int(state.rounds)} if use_block else {}),
        **bf16_gram_stats,
        # Auto-gate provenance (ISSUE 14): present whenever this solve
        # consulted at least one None-valued accelerator knob — each
        # entry says whether the decision came from an installed
        # DeviceProfile (with probe ratio + threshold) or the default.
        **_autotune_embed(),
    }
    if obs.live:
        stats["obs_run_id"] = obs.run_id
        stats["obs_runlog"] = obs.path
        # The per-pair LRU's registry instruments (ISSUE 9 satellite:
        # the cache was invisible to `cli obs report`). Counters ride
        # the same host-held values the stats dict reports — zero
        # device effect, like every obs record.
        if use_cache:
            obs.registry.counter("solve.cache_hits_total").add(cache_hits)
            obs.registry.counter(
                "solve.cache_lookups_total").add(total_lookups)
            obs.registry.counter(
                "solve.cache_evictions_total").add(cache_evictions)
    obs.finish(iterations=it, converged=bool(converged),
               train_seconds=round(train_seconds, 6),
               dispatches=dispatches, b_hi=b_hi, b_lo=b_lo,
               n_sv=int(np.count_nonzero(alpha > 0)),
               phase_seconds=phase_seconds,
               **({"cache_hits": cache_hits,
                   "cache_lookups": total_lookups,
                   "cache_hit_rate": round(hit_rate, 6),
                   "cache_evictions": cache_evictions}
                  if use_cache else {}))
    return SolveResult(
        alpha=alpha,
        b=float((b_lo + b_hi) / 2.0),  # svmTrainMain.cpp:329
        b_hi=b_hi,
        b_lo=b_lo,
        iterations=it,
        converged=converged,
        train_seconds=train_seconds,
        dispatches=dispatches,
        stats=stats,
    )
