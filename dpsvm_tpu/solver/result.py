"""Common result record returned by every solver backend."""

from __future__ import annotations

import dataclasses

import numpy as np


@dataclasses.dataclass
class SolveResult:
    alpha: np.ndarray  # (n,) final dual variables
    b: float  # intercept = (b_lo + b_hi) / 2 (svmTrainMain.cpp:329)
    b_hi: float
    b_lo: float
    iterations: int
    converged: bool
    train_seconds: float = 0.0
    # Device executor dispatches the host loop made for this solve (0 when
    # the backend does not count them). For a fleet member
    # (solver/fleet.py) this is the dispatch count of the WHOLE fleet —
    # shared, not per-problem; stats["fleet"] carries the membership so
    # aggregators can de-duplicate.
    dispatches: int = 0
    stats: dict = dataclasses.field(default_factory=dict)

    @property
    def n_sv(self) -> int:
        return int(np.count_nonzero(np.asarray(self.alpha) > 0))
