"""Common result record returned by every solver backend."""

from __future__ import annotations

import dataclasses

import numpy as np


@dataclasses.dataclass
class SolveResult:
    alpha: np.ndarray  # (n,) final dual variables
    b: float  # intercept = (b_lo + b_hi) / 2 (svmTrainMain.cpp:329)
    b_hi: float
    b_lo: float
    iterations: int
    converged: bool
    train_seconds: float = 0.0
    stats: dict = dataclasses.field(default_factory=dict)

    @property
    def n_sv(self) -> int:
        return int(np.count_nonzero(np.asarray(self.alpha) > 0))
