"""Warm-start carries for the solver stack (ISSUE 18).

Every retrain used to start from the C-SVC cold point (alpha = 0,
f = -y) and re-pay the full SMO trajectory even when a converged model
for nearly the same data already existed. Graf et al.'s Cascade SVM
(PAPERS.md) is the observation this module productizes: a solve seeded
from the support vectors of a previous solution converges in a small
fraction of the iterations. Three pieces:

* :class:`WarmStart` — the carry format: seed alpha values plus an
  optional row map placing them in the NEW training set (the previous
  generation's SVs typically occupy rows ``0..n_sv-1`` when the new
  increment is ``concat(prev.sv_x, fresh_rows)`` —
  :func:`seed_from_model` builds exactly that).
* :func:`repair_seed` — host-f64 feasibility repair: clip the seeded
  alphas into the NEW per-class box (``config.c_bounds()`` — the box
  may have shrunk across generations), rescale the heavier class side
  so both sides carry the same mass, then zero the remaining
  round-off residual of ``sum(alpha_i y_i)`` on a slack coordinate.
  The repaired seed satisfies BOTH dual constraints.
* :func:`warm_f_rebuild` — the gradient from the repaired seed in ONE
  streamed pass over X, reusing the out-of-core tile fold
  (:func:`dpsvm_tpu.ops.ooc.ooc_fold_tile`, ``want_dots=False``) under
  the solver/ooc.py double-buffer structure: tile t+1's host->HBM put
  is issued before tile t's fold dispatch, and every device operand is
  tile- or seed-block-sized, so the same code path serves in-core and
  out-of-core X. There is deliberately NO second Gram-pass
  implementation here: the f64 certification leg is
  :func:`dpsvm_tpu.solver.reconstruct.gram_matvec_f64` (the one shared
  host-f64 kernel definition) and the streamed leg is the one shared
  tile fold — the dedup contract tests/test_warmstart.py pins.
* :func:`warm_rebuild_mesh` — the mesh form: seed rows are gathered
  from the row-sharded X through ONE psum (a one-hot selector matmul,
  the parallel/dist_smo.py ``_gather_row`` discipline widened to the
  whole seed block), then each shard folds its local gradient slice
  with zero further collectives. The tpulint ``warm_f_rebuild`` budget
  pins both forms statically.

The zero-seed contract: a seed that repairs to all-zeros (including
``warm_start=None``) must route BIT-IDENTICALLY through today's cold
path — :func:`prepare_warm_start` returns ``(None, None, stats)`` in
that case so the solvers' existing ``alpha_init is None`` branches run
untouched (pinned per engine in tests/test_warmstart.py).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

# Seed rows are folded in device blocks of this many query rows: a
# FIXED block size (zero-padded tail) so a warm rebuild compiles one
# fold shape per (tile, d) regardless of how many SVs the seed carries.
Q_BLOCK = 256


@dataclasses.dataclass(frozen=True)
class WarmStart:
    """A solver seed: ``alpha[i]`` seeds training row ``rows[i]``.

    ``rows=None`` means ``alpha`` is a full ``(n,)`` vector over the new
    training set. Values are repaired (box + equality) before use, so a
    carry from a DIFFERENT C / class-weight configuration is legal —
    that is the cascade/C-sweep case.
    """

    alpha: np.ndarray
    rows: Optional[np.ndarray] = None

    def dense(self, n: int) -> np.ndarray:
        """The seed as a float64 ``(n,)`` vector."""
        a = np.asarray(self.alpha, np.float64).ravel()
        if self.rows is None:
            if a.shape[0] != n:
                raise ValueError(
                    f"WarmStart without rows wants a full ({n},) alpha "
                    f"vector, got shape {a.shape}")
            return a.copy()
        rows = np.asarray(self.rows, np.int64).ravel()
        if rows.shape != a.shape:
            raise ValueError(
                f"WarmStart rows/alpha length mismatch: {rows.shape} "
                f"vs {a.shape}")
        if rows.size and (rows.min() < 0 or rows.max() >= n):
            raise ValueError(
                f"WarmStart rows out of range for n={n}: "
                f"[{rows.min()}, {rows.max()}]")
        out = np.zeros(n, np.float64)
        out[rows] = a
        return out


def seed_from_model(model) -> WarmStart:
    """The generation-to-generation carry: a prior :class:`SVMModel`'s
    SV alphas seeding rows ``0..n_sv-1`` — the layout of a new
    increment built as ``concat(model.sv_x, fresh_rows)`` (the `cli
    learn` loop's construction)."""
    n_sv = int(model.sv_alpha.shape[0])
    return WarmStart(alpha=np.asarray(model.sv_alpha, np.float64),
                     rows=np.arange(n_sv, dtype=np.int64))


def repair_seed(alpha: np.ndarray, y: np.ndarray, c_bounds: tuple,
                max_fix_rounds: int = 8):
    """Feasibility repair in host float64.

    Returns ``(repaired (n,) f64, stats)`` with the repaired seed
    satisfying ``0 <= a_i <= box_i`` (``box_i = c_pos`` for ``y_i=+1``
    rows, ``c_neg`` for ``y_i=-1`` — the c_of discipline) and
    ``sum(a_i y_i) = 0`` to f64 round-off, driven to exactly 0.0 by a
    slack-coordinate correction loop in the generic case.

    Repair order matters: clipping into a SHRUNK box (a new generation
    trained at smaller C) can unbalance the class sides, so the
    equality restore runs AFTER the clip — each side is scaled DOWN to
    the lighter side's mass (scaling down never leaves the box), then
    the residual lands on one coordinate with room.
    """
    y64 = np.asarray(y, np.float64)
    a = np.asarray(alpha, np.float64).copy()
    n = a.shape[0]
    if y64.shape[0] != n:
        raise ValueError(f"alpha/y length mismatch: {n} vs {y64.shape[0]}")
    c_pos, c_neg = float(c_bounds[0]), float(c_bounds[1])
    box = np.where(y64 > 0, c_pos, c_neg)
    clipped = np.clip(a, 0.0, box)
    n_clipped = int(np.count_nonzero(clipped != a))
    a = clipped
    pos, neg = y64 > 0, y64 <= 0
    s_pos = float(a[pos].sum())
    s_neg = float(a[neg].sum())
    target = min(s_pos, s_neg)
    if target <= 0.0:
        # One side carries no mass: the only feasible point reachable by
        # scaling down is alpha = 0 — the cold start.
        a[:] = 0.0
        return a, {"seed_nnz": 0, "clipped": n_clipped,
                   "side_sums": (s_pos, s_neg), "scaled_to": 0.0,
                   "residual": 0.0, "zero_seed": True}
    if s_pos > target:
        a[pos] *= target / s_pos
    if s_neg > target:
        a[neg] *= target / s_neg
    # Round-off residual: scaling leaves |sum(a y)| at f64 noise; push
    # it onto coordinates with slack until the recomputed sum is
    # exactly zero (typically one pass).
    residual = float(np.dot(a, y64))
    for _ in range(max_fix_rounds):
        if residual == 0.0:
            break
        # a_j -> a_j - r*y_j zeroes the sum iff the move stays in box.
        need = residual * y64  # per-coordinate move, sign-resolved
        ok = (a - need >= 0.0) & (a - need <= box)
        cand = np.nonzero(ok & (a > 0.0))[0]
        if cand.size == 0:
            cand = np.nonzero(ok)[0]
        if cand.size == 0:  # pragma: no cover - degenerate box
            break
        j = int(cand[np.argmax(a[cand])])
        a[j] -= residual * y64[j]
        residual = float(np.dot(a, y64))
    nnz = int(np.count_nonzero(a))
    return a, {"seed_nnz": nnz, "clipped": n_clipped,
               "side_sums": (s_pos, s_neg), "scaled_to": target,
               "residual": residual, "zero_seed": nnz == 0}


def _row_norms_f32(blk: np.ndarray) -> np.ndarray:
    return np.einsum("ij,ij->i", blk, blk).astype(np.float32)


def warm_f_rebuild(x, y, alpha: np.ndarray, kp, device=None,
                   tile_rows: int = 8192,
                   q_block: int = Q_BLOCK) -> np.ndarray:
    """The C-SVC gradient ``f = K (alpha*y) - y`` from a repaired seed,
    in ONE streamed pass over X.

    Structure is the solver/ooc.py round stream: host X is read once in
    ``tile_rows`` blocks through the same ``_tile_host`` reader, tile
    t+1's ``device_put`` is issued before tile t's fold dispatches (the
    double buffer), and each tile's gradient slice is folded by the ONE
    shared tile kernel — :func:`dpsvm_tpu.ops.ooc.ooc_fold_tile` with
    ``want_dots=False`` (no cache currency; the warm path never
    materializes dot rows). Seed rows ride as device-resident
    ``q_block``-sized query blocks (zero coefficient padding is inert in
    ``coef @ K``), so the compiled fold is a pure function of
    ``(tile_rows, d, q_block)`` — never of n or of the SV count.

    Works identically for in-core and out-of-core callers: both hold X
    on the host at solve() entry; only who keeps it resident afterwards
    differs.
    """
    import jax
    import jax.numpy as jnp

    from dpsvm_tpu.ops.ooc import ooc_fold_tile
    from dpsvm_tpu.solver.ooc import _tile_host

    x = np.asarray(x)
    n, d = x.shape
    y_np = np.asarray(y, np.float32)
    coef = (np.asarray(alpha, np.float64)
            * np.asarray(y, np.float64)).astype(np.float32)
    f = (-y_np).astype(np.float32).copy()
    nz = np.nonzero(coef != 0.0)[0]
    if nz.size == 0:
        return f
    if device is None:
        device = jax.devices()[0]

    # Seed query blocks: gathered host-side, padded to q_block, resident
    # on device across the whole tile stream (SV counts are small next
    # to n — the cascade premise).
    qblocks = []
    for s in range(0, nz.size, q_block):
        idx = nz[s:s + q_block]
        qx = np.zeros((q_block, d), np.float32)
        qx[:idx.size] = np.asarray(x[idx], np.float32)
        qc = np.zeros((q_block,), np.float32)
        qc[:idx.size] = coef[idx]
        qblocks.append((jax.device_put(jnp.asarray(qx), device),
                        jax.device_put(jnp.asarray(_row_norms_f32(qx)),
                                       device),
                        jax.device_put(jnp.asarray(qc), device)))

    tile = max(1, min(int(tile_rows), n))
    tiles = -(-n // tile)

    def _put(i):
        blk = _tile_host(x, i * tile, tile, n, d)
        return (jax.device_put(jnp.asarray(blk), device),
                jax.device_put(jnp.asarray(_row_norms_f32(blk)), device))

    nxt = _put(0)
    for i in range(tiles):
        cur, nxt = nxt, (_put(i + 1) if i + 1 < tiles else None)
        s = i * tile
        t_real = min(tile, n - s)
        ft = jnp.zeros((tile,), jnp.float32)
        ft = ft.at[:t_real].set(f[s:s + t_real])
        for qx_d, qsq_d, qc_d in qblocks:
            ft, _, _ = ooc_fold_tile(cur[0], cur[1], ft, None,
                                     qx_d, qsq_d, qc_d, kp=kp,
                                     want_dots=False, compensated=False)
        f[s:s + t_real] = np.asarray(ft)[:t_real]
    return f


def _warm_fold_mesh_factory(num_devices: int, kp, d: int,
                            q_block: int = Q_BLOCK):
    """The mesh warm-rebuild program: gather the seed block from the
    row-sharded X through ONE psum, then fold each shard's gradient
    slice locally.

    Per dispatch: ``selT_loc`` is the (n_loc, q_block) one-hot seed
    selector columns this shard owns; the packed local contribution
    ``selT_loc.T @ [x_loc | xsq_loc | coef_loc]`` psums into the full
    (q_block, d+2) seed operand on every device — the ONLY collective —
    and the local fold ``f_loc + qcoef @ kernel(qx, x_loc)`` needs none.
    The carried gradient shard is donated (the tile fold's donation
    discipline; tpulint pins missed=0).
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from dpsvm_tpu.ops.kernels import kernel_from_dots
    from dpsvm_tpu.parallel.mesh import (DATA_AXIS, make_data_mesh,
                                         mesh_shard_map)

    mesh = make_data_mesh(num_devices)

    def body(x_loc, xsq_loc, f_loc, selT_loc, coef_loc):
        with jax.named_scope("warm_fold_mesh"):
            packed = jnp.concatenate(
                [x_loc, xsq_loc[:, None], coef_loc[:, None]], axis=1)
            seed = jax.lax.psum(
                jnp.dot(selT_loc.T, packed,
                        preferred_element_type=jnp.float32), DATA_AXIS)
            qx, qsq, qcoef = seed[:, :d], seed[:, d], seed[:, d + 1]
            dots = jnp.dot(qx, x_loc.T,
                           preferred_element_type=jnp.float32)
            k = kernel_from_dots(dots, xsq_loc, qsq, kp)
            return f_loc + qcoef @ k

    mapped = jax.jit(mesh_shard_map(
        body, mesh=mesh,
        in_specs=(P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS),
                  P(DATA_AXIS), P(DATA_AXIS)),
        out_specs=P(DATA_AXIS), check=False), donate_argnums=(2,))
    return mesh, mapped


def warm_rebuild_mesh(x, y, alpha: np.ndarray, kp,
                      num_devices: int,
                      q_block: int = Q_BLOCK) -> np.ndarray:
    """Mesh form of :func:`warm_f_rebuild`: same contract, gradient
    computed shard-resident with exactly one psum per seed block. Rows
    pad to the mesh's multiple with zero selector/coefficient columns
    (inert in both the psum'd gather and the fold)."""
    import numpy as _np

    from dpsvm_tpu.parallel.mesh import shard_padded_rows

    x = _np.asarray(x, _np.float32)
    n, d = x.shape
    y_np = _np.asarray(y, _np.float32)
    coef = (_np.asarray(alpha, _np.float64)
            * _np.asarray(y, _np.float64)).astype(_np.float32)
    f = (-y_np).astype(_np.float32).copy()
    nz = _np.nonzero(coef != 0.0)[0]
    if nz.size == 0:
        return f
    mesh, mapped = _warm_fold_mesh_factory(num_devices, kp, d,
                                           q_block=q_block)
    xsq = _row_norms_f32(x)
    x_d = shard_padded_rows(mesh, x)
    xsq_d = shard_padded_rows(mesh, xsq)
    n_pad = int(x_d.shape[0])
    f_pad = _np.zeros(n_pad, _np.float32)
    f_pad[:n] = f
    f_d = shard_padded_rows(mesh, f_pad)
    coef_pad = _np.zeros(n_pad, _np.float32)
    coef_pad[:n] = coef
    coef_d = shard_padded_rows(mesh, coef_pad)
    for s in range(0, nz.size, q_block):
        idx = nz[s:s + q_block]
        selT = _np.zeros((n_pad, q_block), _np.float32)
        selT[idx, _np.arange(idx.size)] = 1.0
        f_d = mapped(x_d, xsq_d, f_d, shard_padded_rows(mesh, selT),
                     coef_d)
    return _np.asarray(f_d)[:n]


def prepare_warm_start(x, y, config, warm: Optional[WarmStart],
                       device=None, mesh_devices: Optional[int] = None):
    """Repair + rebuild: the solvers' warm front door.

    Returns ``(alpha_init, f_init, stats)`` as float32 host arrays ready
    for the existing ``alpha_init``/``f_init`` plumbing — or
    ``(None, None, stats)`` when the repaired seed is all-zero, so the
    caller's ``alpha_init is None`` branch routes BIT-IDENTICALLY
    through today's cold path (the pinned contract).

    ``mesh_devices > 1`` rebuilds through the one-psum mesh fold
    (solve_mesh's path); otherwise the single-chip tile stream.
    """
    x = np.asarray(x)
    n, d = x.shape
    stats: dict = {"seed_rows": 0}
    if warm is None:
        return None, None, {**stats, "zero_seed": True}
    dense = warm.dense(n)
    stats["seed_rows"] = int(np.count_nonzero(dense))
    repaired, rstats = repair_seed(dense, y, config.c_bounds())
    stats.update(rstats)
    if rstats["zero_seed"]:
        return None, None, stats
    gamma = config.resolve_gamma(d)
    from dpsvm_tpu.ops.kernels import KernelParams

    kp = KernelParams(config.kernel, gamma, config.degree, config.coef0)
    if mesh_devices and mesh_devices > 1:
        f = warm_rebuild_mesh(x, y, repaired, kp, mesh_devices)
    else:
        f = warm_f_rebuild(x, y, repaired, kp, device=device,
                           tile_rows=int(config.ooc_tile_rows))
    return repaired.astype(np.float32), f, stats
