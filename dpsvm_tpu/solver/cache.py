"""Functional LRU cache of kernel dot-product rows, resident in HBM.

TPU-native re-design of the reference's myCache (cache.hpp:23-43,
cache.cu:49-105): there, preallocated device vectors hold dot-product rows
and a host-side std::map + std::list implement LRU; here the whole cache is
three static-shape arrays living inside the jitted while_loop carry:

    data  (L, n) float32 -- the cached dot rows (like the reference, the
                            cache stores DOT rows, not exp'd kernel rows;
                            the kernel transform is recomputed per use,
                            cache.cu line semantics / svmTrain.cu:128-131)
    keys  (L,)  int32    -- training-row index held by each line (-1 empty)
    ticks (L,)  int32    -- last-use stamp; eviction = argmin(ticks)

This fixes reference bug B7 (O(cache) list::remove per hit) trivially: hit
refresh is one scatter. Both working-set rows are looked up at once so a
double miss costs ONE (2,d)x(d,n) MXU pass over X instead of two.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from dpsvm_tpu.ops.kernels import row_dots

_I32_MAX = jnp.iinfo(jnp.int32).max


class CacheState(NamedTuple):
    data: jax.Array  # (L, n) float32
    keys: jax.Array  # (L,) int32
    ticks: jax.Array  # (L,) int32


def init_cache(lines: int, n: int) -> CacheState:
    # Negative, ordered ticks make empty lines fill in slot order before any
    # real eviction happens (real stamps are >= 1).
    return CacheState(
        data=jnp.zeros((lines, n), jnp.float32),
        keys=jnp.full((lines,), -1, jnp.int32),
        ticks=(jnp.arange(lines, dtype=jnp.int32) - lines),
    )


def lookup_pair(
    cache: CacheState,
    x: jax.Array,
    i_hi: jax.Array,
    i_lo: jax.Array,
    q_hi: jax.Array,
    q_lo: jax.Array,
    it: jax.Array,
):
    """Fetch dot rows for both working-set indices, updating the cache.

    Returns (row_hi, row_lo, new_cache, n_hits) with rows float32 (n,).
    Equivalent role: SvmTrain::lookup_cache + get_new_cache_line
    (svmTrain.cu:142-156, cache.cu:62-105), fused for the pair.
    """
    lines = cache.keys.shape[0]
    hit_hi_vec = cache.keys == i_hi
    hit_lo_vec = cache.keys == i_lo
    hit_hi = jnp.any(hit_hi_vec)
    hit_lo = jnp.any(hit_lo_vec)

    slot_hi = jnp.where(hit_hi, jnp.argmax(hit_hi_vec), jnp.argmin(cache.ticks))
    slot_hi = slot_hi.astype(jnp.int32)
    # Keep the second lookup off the first one's slot so a double miss fills
    # two distinct lines.
    ticks_masked = jnp.where(
        jnp.arange(lines, dtype=jnp.int32) == slot_hi, _I32_MAX, cache.ticks)
    slot_lo = jnp.where(hit_lo, jnp.argmax(hit_lo_vec), jnp.argmin(ticks_masked))
    slot_lo = slot_lo.astype(jnp.int32)

    def both_miss(_):
        d2 = row_dots(x, jnp.stack([q_hi, q_lo]))
        return d2[0], d2[1]

    def hi_hit_only(_):
        return _read(cache.data, slot_hi), row_dots(x, q_lo)

    def lo_hit_only(_):
        return row_dots(x, q_hi), _read(cache.data, slot_lo)

    def both_hit(_):
        return _read(cache.data, slot_hi), _read(cache.data, slot_lo)

    # case = 2*hit_hi + hit_lo: 0 = both miss, 1 = only lo hit,
    # 2 = only hi hit, 3 = both hit.
    case = hit_hi.astype(jnp.int32) * 2 + hit_lo.astype(jnp.int32)
    row_hi, row_lo = lax.switch(case, [both_miss, lo_hit_only, hi_hit_only, both_hit], None)

    stamp = 2 * it.astype(jnp.int32)
    new_cache = CacheState(
        data=cache.data.at[slot_hi].set(row_hi).at[slot_lo].set(row_lo),
        keys=cache.keys.at[slot_hi].set(i_hi).at[slot_lo].set(i_lo),
        ticks=cache.ticks.at[slot_hi].set(stamp + 1).at[slot_lo].set(stamp + 2),
    )
    n_hits = hit_hi.astype(jnp.int32) + hit_lo.astype(jnp.int32)
    return row_hi, row_lo, new_cache, n_hits


def lookup_one(
    cache: CacheState,
    x: jax.Array,
    i: jax.Array,
    q: jax.Array,
    stamp: jax.Array,
):
    """Fetch the dot row for a single index (used by second-order selection,
    which must see row i before choosing j). Returns (row, new_cache, hit)."""
    hit_vec = cache.keys == i
    hit = jnp.any(hit_vec)
    slot = jnp.where(hit, jnp.argmax(hit_vec), jnp.argmin(cache.ticks))
    slot = slot.astype(jnp.int32)
    row = lax.cond(
        hit,
        lambda _: _read(cache.data, slot),
        lambda _: row_dots(x, q),
        None)
    new_cache = CacheState(
        data=cache.data.at[slot].set(row),
        keys=cache.keys.at[slot].set(i),
        ticks=cache.ticks.at[slot].set(stamp),
    )
    return row, new_cache, hit


def _read(data: jax.Array, slot: jax.Array) -> jax.Array:
    return lax.dynamic_index_in_dim(data, slot, axis=0, keepdims=False)


# --------------------------------------------------------------------
# Block-engine extension (ISSUE 9): the same static-shape data/keys/
# ticks discipline, probed and refreshed for a whole q-sized working
# set at once instead of one pair. Used by the out-of-core driver
# (solver/ooc.py): an all-hit round reads its fold rows straight from
# HBM and skips the host->HBM tile stream entirely.

def probe_rows(keys: jax.Array, w: jax.Array, slot_ok: jax.Array):
    """Batched cache probe: which working-set slots hold a cached row.

    keys (L,) int32; w (q,) int32; slot_ok (q,) bool (dead filler slots
    never count as hits). Returns (hit (q,) bool, hit_slot (q,) int32 —
    junk where ~hit)."""
    hit_mat = keys[None, :] == w[:, None]  # (q, L)
    hit = jnp.any(hit_mat, axis=1) & slot_ok
    hit_slot = jnp.argmax(hit_mat, axis=1).astype(jnp.int32)
    return hit, hit_slot


def refresh_rows(cache: CacheState, w: jax.Array, slot_ok: jax.Array,
                 rows: jax.Array, stamp: jax.Array):
    """Scatter-refresh the whole working set in one static-shape pass:
    hits overwrite their own line (and re-stamp), misses claim the q
    least-recently-used lines (hit lines masked out of the victim
    pool). Requires L >= q — one round's misses must always fit, which
    is what SVMConfig.ooc_cache_lines validates.

    rows (q, n): the freshly computed dot rows for every slot (hits
    included — rewriting a hit with identical values is cheaper than a
    gather/select dance, and keeps the write static-shape). Dead slots
    (slot_ok False) never scatter.

    Returns (new_cache, n_hits, n_evictions) with the counters int32 —
    an eviction is a live miss landing on a line that held a real key.
    """
    lines = cache.keys.shape[0]
    q = w.shape[0]
    hit, hit_slot = probe_rows(cache.keys, w, slot_ok)
    # Victim pool: the q least-recently-used lines, never a line a hit
    # is about to refresh (its row must survive this round's write).
    line_hit = jnp.zeros((lines,), bool).at[
        jnp.where(hit, hit_slot, jnp.int32(lines))].set(
        True, mode="drop")
    ticks_m = jnp.where(line_hit, _I32_MAX, cache.ticks)
    _, victims = lax.top_k(-ticks_m, q)  # ascending ticks
    # 0-based victim rank among LIVE miss slots only: dead filler slots
    # must not consume victim lines (they never scatter).
    miss_rank = jnp.cumsum(slot_ok & ~hit) - 1
    slot = jnp.where(hit, hit_slot,
                     victims[jnp.clip(miss_rank, 0, q - 1)])
    slot = slot.astype(jnp.int32)
    n_evict = jnp.sum((slot_ok & ~hit)
                      & (jnp.take(cache.keys, slot) >= 0))
    safe = jnp.where(slot_ok, slot, jnp.int32(lines))
    new_cache = CacheState(
        data=cache.data.at[safe].set(
            jnp.where(slot_ok[:, None], rows, 0.0), mode="drop"),
        keys=cache.keys.at[safe].set(
            jnp.where(slot_ok, w, -1), mode="drop"),
        ticks=cache.ticks.at[safe].set(stamp, mode="drop"),
    )
    return new_cache, jnp.sum(hit).astype(jnp.int32), \
        n_evict.astype(jnp.int32)
