"""Distributed blockwise decomposition engine over the data mesh.

The mesh counterpart of solver/block.py, and the design the reference's
communication pattern becomes when re-derived for ICI: where the reference
allgathers ONE candidate pair per rank per pair update (4 floats/rank/
iteration, svmTrainMain.cpp:244 — latency-bound on Ethernet), this engine
allgathers the per-shard top-q/2 violator candidates ONCE per round,
solves the replicated q-variable subproblem on every device (the same
replicated-update trick the reference uses for its alpha-pair algebra,
svmTrainMain.cpp:285-299, lifted from 1 pair to q variables), and folds
the round's alpha deltas into the SHARDED gradient with a purely local
(q, n_loc) matmul — zero communication in the fold.

Per round, per device:
  1. local top-h of I_up (smallest f) and I_low (largest f), h = q/2
  2. all_gather candidates -> replicated global top-h per side + dedupe
     (the union of per-shard top-h contains the global top-h, so W always
     holds the globally most-violating pair — the convergence invariant)
  3. one masked-psum recovers the W rows (q, d) + their per-row scalars
  4. replicated on-core subproblem solve (identical on every device)
  5. local fold f_loc += coef @ K(W, shard); owned alpha slots scattered

The stopping extrema b_hi/b_lo ride step 2's gathered candidate values
(every device reduces the same gathered tops, so the result is replicated
with zero extra collectives); the loop carry is therefore one fold behind,
compensated exactly as in solver/block.py run_chunk_block.

Steady-state traffic per ROUND: one (h,2) f32 + (h,2) i32 all_gather pair
and one (q, d+5) psum — a few hundred KB amortized over ~q pair updates,
vs the reference's per-update 16P-byte latency-bound allgather.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from dpsvm_tpu.ops.kernels import KernelParams, kernel_from_dots, kernel_rows
from dpsvm_tpu.ops.select import (candidate_live_mask, low_mask,
                                  nu_stopping_pair, split_c,
                                  stopping_extrema, up_mask)
from dpsvm_tpu.parallel.dist_smo import _global_ids
from dpsvm_tpu.parallel.mesh import DATA_AXIS, mesh_shard_map
from dpsvm_tpu.solver.block import (BlockState, _round_core,
                                    _solve_subproblem, _top_h,
                                    combine_halves, run_local_round)
from dpsvm_tpu.solver.smo import eff_f, maybe_kahan


def _global_top(scores, gids_loc, h: int):
    """Replicated global top-h PER ROW from per-shard top-h candidates.

    scores: (r, n_loc) score rows with -inf at inadmissible entries — all
    candidate sides ride one batched selection + all_gather dispatch
    sequence (same batching as the single-chip select_block). Returns
    (g_ids (r, h), ok (r, h), vals (r, h)) — identical on every device
    (every device reduces the same gathered candidates; vals are the
    gathered top scores, whose row maxima are the exact global extrema) —
    though WHICH mid-rank candidates surface is not index-stable under
    ties on TPU (approx_max_k's bin layout, not lowest-id order; each
    row's true extremum is always included)."""
    r = scores.shape[0]
    # Local stage: TPU-native approximate top-k (exact maxima, ~1-2%
    # recall on the tail; see solver/block.py _top_h). The global stage
    # below stays exact — it reduces only (P*h,) gathered candidates.
    v, i = _top_h(scores, h)  # (r, h)
    g = jnp.take(gids_loc, i)
    # named_scope = op METADATA only (stage names in device traces;
    # opcode structure/counts untouched — the tpulint budgets pin it).
    with jax.named_scope("mesh_candidate_gather"):
        av = lax.all_gather(v, DATA_AXIS)  # (P, r, h)
        ag = lax.all_gather(g, DATA_AXIS)
    av = jnp.moveaxis(av, 0, 1).reshape(r, -1)  # (r, P*h), device-major
    ag = jnp.moveaxis(ag, 0, 1).reshape(r, -1)
    gv, gi = lax.top_k(av, h)
    return jnp.take_along_axis(ag, gi, axis=1), jnp.isfinite(gv), gv


def _select_block_mesh(f, alpha, y, valid, c, q: int, rule: str = "mvp"):
    """Distributed working-set selection; replicated (w, slot_ok, b_hi,
    b_lo) result. Same semantics as solver/block.py select_block (rule=
    "nu" -> per-class quarters, one equality constraint per class; the
    extrema are the larger-violation class's pair). The extrema are exact
    and globally reduced: the local stage always retains each score row's
    true maximum and the gathered global stage is an exact top_k, so every
    device computes the identical b_hi/b_lo with zero extra collectives."""
    cp, cn = split_c(c)
    n_loc = f.shape[0]
    gids = _global_ids(n_loc)
    up = up_mask(alpha, y, cp, cn) & valid
    low = low_mask(alpha, y, cp, cn) & valid
    if rule == "nu":
        pos = y > 0
        h = q // 4
        scores = jnp.stack([jnp.where(up & pos, -f, -jnp.inf),
                            jnp.where(low & pos, f, -jnp.inf),
                            jnp.where(up & ~pos, -f, -jnp.inf),
                            jnp.where(low & ~pos, f, -jnp.inf)])
        ids, ok, gv = _global_top(scores, gids, h)
        w_p, ok_p = combine_halves(ids[0], ok[0], ids[1], ok[1])
        w_n, ok_n = combine_halves(ids[2], ok[2], ids[3], ok[3])
        b_hi, b_lo = nu_stopping_pair(-jnp.max(gv[0]), jnp.max(gv[1]),
                                      -jnp.max(gv[2]), jnp.max(gv[3]))
        return (jnp.concatenate([w_p, w_n]),
                jnp.concatenate([ok_p, ok_n]), b_hi, b_lo)
    h = q // 2
    scores = jnp.stack([jnp.where(up, -f, -jnp.inf),
                        jnp.where(low, f, -jnp.inf)])
    ids, ok, gv = _global_top(scores, gids, h)
    w, slot_ok = combine_halves(ids[0], ok[0], ids[1], ok[1])
    return w, slot_ok, -jnp.max(gv[0]), jnp.max(gv[1])


def _check_ring(ring_exchange: bool, mesh: Mesh, kp: KernelParams,
                selection: str) -> None:
    """Factory-time guard for the ring-exchange runners: the ring
    carries feature rows (a precomputed Gram has none to carry and its
    symmetric round is already collective-light), the two-sided rules
    only (the nu rule's per-class quarters keep the all_gather path,
    same restriction as pipelined/fused), and at least two devices (a
    one-device 'ring' has no hops — solve_mesh routes the plain
    exchange there)."""
    if not ring_exchange:
        return
    if kp.kind == "precomputed":
        raise ValueError(
            "ring_exchange supports feature kernels only (a precomputed "
            "Gram has no rows for the candidate ring to carry; its "
            "symmetric round is already collective-light)")
    if selection not in ("mvp", "second_order"):
        raise ValueError(
            "ring_exchange supports selection in {'mvp', 'second_order'} "
            "(the nu rule's per-class quarters keep the all_gather path)")
    if int(mesh.devices.size) < 2:
        raise ValueError(
            "ring_exchange needs >= 2 devices (a one-device ring has no "
            "hops; use the plain runner)")


def _select_block_mesh_ring(f, alpha, y, valid, c, q: int, data_loc,
                            ndev: int, interpret: bool):
    """Ring-exchange counterpart of _select_block_mesh + _gather_ws for
    the two-sided rules (ISSUE 11): each shard's per-side top-h
    candidates travel the ICI ring as (2h, L+2) blocks of
    [data row | score | gid bits] (ops/ring.py ring_gather), so
    selection AND working-set recovery complete with ZERO XLA
    collectives — the rows and per-row scalars arrive WITH the
    candidates, eliminating the (q, d) + (q, S) recovery psums.

    data_loc: (n_loc, L) f32 [x rows (d, widened) | per-row scalar
    stack] — the lanes each winning slot needs downstream. Returns
    (w, slot_ok, b_hi, b_lo, wdata (q, L)) with wdata ordered exactly
    like combine_halves' [up | low] concat.

    Bit-identity with the all_gather path (pinned in tests/test_ring.py):
    the gathered candidate axis is reassembled device-major — the same
    (r, P*h) layout `_global_top` builds — so the exact global top_k
    picks identical winners (ties included); winner rows/scalars are the
    owner's bits (the masked psum recovers the same values, as all
    non-owner contributions are exact zeros); dead filler slots carry
    finite real-row data either way and are masked by slot_ok
    everywhere downstream. Global ids ride TWO value lanes as an exact
    12/19-bit split — the docs/ARCHITECTURE.md numerics rule: a bitcast
    int32 with a small payload reads as an f32 DENORMAL, which TPU data
    paths may flush to zero; split values stay normal and exact."""
    from dpsvm_tpu.ops.ring import ring_gather

    cp, cn = split_c(c)
    n_loc = f.shape[0]
    gids = _global_ids(n_loc)
    up = up_mask(alpha, y, cp, cn) & valid
    low = low_mask(alpha, y, cp, cn) & valid
    h = q // 2
    scores = jnp.stack([jnp.where(up, -f, -jnp.inf),
                        jnp.where(low, f, -jnp.inf)])
    v, i = _top_h(scores, h)  # (2, h) local stage, as _global_top
    g = jnp.take(gids, i).reshape(-1, 1)
    flat = i.reshape(-1)  # side-major (2h,): [up half | low half]
    data = jnp.take(data_loc, flat, axis=0)  # (2h, L)
    g_hi = (g >> 12).astype(jnp.float32)   # < 2^19: exact in f32
    g_lo = (g & 0xFFF).astype(jnp.float32)  # < 2^12: exact in f32
    blk = jnp.concatenate([data, v.reshape(-1, 1), g_hi, g_lo], axis=1)
    with jax.named_scope("mesh_candidate_ring"):
        ag = ring_gather(blk, ndev, interpret=interpret)  # (P, 2h, L+3)
    lanes = data_loc.shape[1]
    cand = jnp.moveaxis(ag.reshape(ndev, 2, h, lanes + 3), 0, 1)
    cand = cand.reshape(2, ndev * h, lanes + 3)  # device-major, like
    av = cand[:, :, lanes]                       # _global_top's av/ag
    agid = (cand[:, :, lanes + 1].astype(jnp.int32) << 12) \
        | cand[:, :, lanes + 2].astype(jnp.int32)
    gv, gi = lax.top_k(av, h)
    ids = jnp.take_along_axis(agid, gi, axis=1)
    win = jnp.take_along_axis(cand[:, :, :lanes], gi[:, :, None], axis=1)
    w, slot_ok = combine_halves(ids[0], jnp.isfinite(gv[0]),
                                ids[1], jnp.isfinite(gv[1]))
    wdata = jnp.concatenate([win[0], win[1]], axis=0)  # (q, L)
    return w, slot_ok, -jnp.max(gv[0]), jnp.max(gv[1]), wdata


def _ws_owners(w, slot_ok, n_loc: int):
    """Per-device ownership of the replicated working-set ids: (l local
    slot index, own mask, l_safe clipped index). THE single definition of
    the shard-offset convention — every gather/scatter derives from it."""
    dev = lax.axis_index(DATA_AXIS)
    l = w - dev.astype(jnp.int32) * n_loc
    own = (l >= 0) & (l < n_loc) & slot_ok
    return l, own, jnp.clip(l, 0, n_loc - 1)


def _psum_scal(scal_loc, own, l_safe):
    """Replicate the working set's per-row scalars: one (q, S) psum."""
    return lax.psum(jnp.where(own[:, None],
                              jnp.take(scal_loc, l_safe, axis=0), 0.0),
                    DATA_AXIS)


def _gather_ws(x_loc, scal_loc, w, slot_ok, n_loc: int):
    """Recover the working set's rows and per-row scalars from the shards
    with one (q, d) + one (q, S) psum. scal_loc: (n_loc, S) stacked
    per-row scalars. Returns (qx (q, d) f32, scal (q, S) f32, l (q,) i32,
    own (q,) bool); qx/scal are replicated across devices, while l (local
    slot index) and own (this-shard ownership mask) are PER-DEVICE."""
    l, own, l_safe = _ws_owners(w, slot_ok, n_loc)
    with jax.named_scope("mesh_ws_recover"):
        qx_own = jnp.where(own[:, None], jnp.take(x_loc, l_safe, axis=0)
                           .astype(jnp.float32), 0.0)
        qx = lax.psum(qx_own, DATA_AXIS)
        scal = _psum_scal(scal_loc, own, l_safe)
    return qx, scal, l, own



def _mesh_round_core(x_loc, x_sq_loc, scal_loc, w, slot_ok, gap_open,
                     budget_left, kp, c, eps, tau, inner_iters: int,
                     inner_impl: str, interpret: bool, selection: str,
                     pair_batch: int = 1, ring_ws=None):
    """The shared mesh round step AFTER selection: working-set recovery
    (masked psum, or the symmetric local path for a precomputed Gram),
    the replicated (q, q) Gram block + subproblem solve (every device
    computes the identical result — the reference's replicated-update
    trick, svmTrainMain.cpp:285-299, lifted to q variables), the fold
    coefficients, and the LOCAL fold rows K(W, shard). Used by the plain
    and fused runners; the active runner works on replicated views via
    solver/block.py _round_core instead.

    `scal_loc` is the (n_loc, 5) stack [x_sq, k_diag, alpha, y, f_eff].
    `ring_ws`, when set, is the ring exchange's (qx (q, d) f32,
    scal (q, 5) f32) — the working set already arrived WITH the
    candidates (_select_block_mesh_ring), so the recovery psums are
    skipped entirely. Returns (alpha_w, coef, t, l, own, k_rows_loc)."""
    n_loc = x_loc.shape[0]
    if ring_ws is not None:
        qx, scal = ring_ws
        l, own, _ = _ws_owners(w, slot_ok, n_loc)
        qsq = scal[:, 0]
    elif kp.kind == "precomputed":
        # x_loc holds this shard's ROWS of the (symmetric) Gram matrix.
        # Symmetry makes everything local or tiny: K(W, W) = psum of
        # each shard's owned rows' W-columns ((q, q) traffic — never the
        # (q, n) row psum), and the fold's K(W, shard) is the transpose
        # of the LOCAL column gather x_loc[:, W] (zero traffic).
        l, own, l_safe = _ws_owners(w, slot_ok, n_loc)
        scal = _psum_scal(scal_loc, own, l_safe)
        rows_own = jnp.where(
            own[:, None],
            jnp.take(x_loc, l_safe, axis=0).astype(jnp.float32),
            0.0)  # (q, n_pad) — local view of the owned W rows
        kb_w = lax.psum(jnp.take(rows_own, w, axis=1), DATA_AXIS)
        qx = qsq = None
    else:
        qx, scal, l, own = _gather_ws(x_loc, scal_loc, w, slot_ok, n_loc)
        qsq = scal[:, 0]
    kd_w, alpha_w0, y_w, f_w0 = (
        scal[:, 1], scal[:, 2], scal[:, 3], scal[:, 4])

    if kp.kind != "precomputed":
        dots_w = jnp.dot(qx, qx.T, preferred_element_type=jnp.float32)
        kb_w = kernel_from_dots(dots_w, qsq, qsq, kp)
    limit = jnp.minimum(jnp.int32(inner_iters), budget_left)
    limit = jnp.where(gap_open, limit, 0)
    if inner_impl == "pallas":
        from dpsvm_tpu.ops.pallas_subproblem import solve_subproblem_pallas

        alpha_w, t = solve_subproblem_pallas(
            kb_w, alpha_w0, y_w, f_w0, kd_w,
            slot_ok.astype(jnp.float32), limit, c, eps, tau,
            rule=selection, interpret=interpret, pair_batch=pair_batch)
    else:
        alpha_w, _, t = _solve_subproblem(
            kb_w, kd_w, slot_ok, alpha_w0, y_w, f_w0, c, eps, tau,
            limit, rule=selection, pair_batch=pair_batch)

    coef = jnp.where(slot_ok, (alpha_w - alpha_w0) * y_w, 0.0)
    if kp.kind == "precomputed":
        k_rows_loc = jnp.take(x_loc, w, axis=1).astype(jnp.float32).T
    else:
        k_rows_loc = kernel_rows(
            x_loc, x_sq_loc, qx.astype(x_loc.dtype), qsq, kp)
    return alpha_w, coef, t, l, own, k_rows_loc


def _jit_runner(mapped, donate_state: bool):
    """jit a chunk runner, optionally donating the BlockState carry
    (arg 5). The solve driver (dist_smo.py) donates — its host loop
    rebinds `state = run_chunk(...)` and never re-reads the old carry,
    so the input alpha/f shards leave the live set per dispatch. The
    default stays undonated for probes that legitimately re-dispatch a
    warmed state (tools/profile_round.py). tpulint budgets pin the
    donated facts on the driver configuration."""
    return jax.jit(mapped, donate_argnums=(5,) if donate_state else ())


def make_block_chunk_runner(mesh: Mesh, kp: KernelParams, c, eps: float,
                            tau: float, q: int, inner_iters: int,
                            rounds_per_chunk: int, inner_impl: str = "xla",
                            interpret: bool = False,
                            selection: str = "mvp",
                            compensated: bool = False,
                            pair_batch: int = 1,
                            donate_state: bool = False,
                            ring_exchange: bool = False):
    """Build the jitted shard_mapped block-round chunk executor.
    selection: "mvp" | "second_order" | "nu" (solver/block.py rules).
    compensated: carry a shard-local Kahan residual of f so the fold's
    fp32 rounding is deferred (solver/smo.py kahan_add).
    ring_exchange: route the round's candidate exchange AND working-set
    recovery through the Pallas ICI ring (_select_block_mesh_ring /
    ops/ring.py) instead of the all_gather + psum pair — bit-identical
    trajectories, zero XLA collectives in the device-form round body
    (config.ring_exchange; tpulint `mesh_chunk_ring` pins it)."""
    _check_ring(ring_exchange, mesh, kp, selection)

    def chunk_body(x_loc, y_loc, x_sq_loc, k_diag_loc, valid_loc,
                   state: BlockState, max_iter):
        n_loc = x_loc.shape[0]
        end = state.rounds + rounds_per_chunk

        def cond(st: BlockState):
            return ((st.rounds < end) & (st.pairs < max_iter)
                    & (st.b_lo > st.b_hi + 2.0 * eps))

        def body(st: BlockState):
            # ONE distributed selection per round: the candidate gather
            # also yields the stopping extrema of the CURRENT f (see
            # solver/block.py run_chunk_block for the one-fold-behind
            # convergence semantics; the final round runs gated to 0
            # pair updates).
            f_cur = eff_f(st)
            if ring_exchange:
                # Candidates + their rows/scalars arrive together over
                # the DMA ring; no recovery psums downstream.
                scal_loc = jnp.stack(
                    [x_sq_loc, k_diag_loc, st.alpha, y_loc, f_cur],
                    axis=1)
                d_feat = x_loc.shape[1]
                data_loc = jnp.concatenate(
                    [x_loc.astype(jnp.float32), scal_loc], axis=1)
                w, slot_ok, b_hi, b_lo, wdata = _select_block_mesh_ring(
                    f_cur, st.alpha, y_loc, valid_loc, c, q, data_loc,
                    int(mesh.devices.size), interpret)
                ring_ws = (wdata[:, :d_feat], wdata[:, d_feat:])
                gap_open = b_lo > b_hi + 2.0 * eps
            else:
                # The all_gather path traces in the ORIGINAL statement
                # order so the ring_exchange=False program (and its
                # committed tpulint budget) stays byte-identical.
                w, slot_ok, b_hi, b_lo = _select_block_mesh(
                    f_cur, st.alpha, y_loc, valid_loc, c, q,
                    rule=selection)
                gap_open = b_lo > b_hi + 2.0 * eps
                scal_loc = jnp.stack(
                    [x_sq_loc, k_diag_loc, st.alpha, y_loc, f_cur],
                    axis=1)
                ring_ws = None
            alpha_w, coef, t, l, own, k_rows_loc = _mesh_round_core(
                x_loc, x_sq_loc, scal_loc, w, slot_ok, gap_open,
                max_iter - st.pairs, kp, c, eps, tau, inner_iters,
                inner_impl, interpret, selection, pair_batch=pair_batch,
                ring_ws=ring_ws)
            # Fold: purely LOCAL (q, n_loc) kernel-row matmul (or, for
            # a precomputed Gram, the symmetric local column gather).
            f, f_err = maybe_kahan(st.f, st.f_err, coef @ k_rows_loc)

            # Scatter owned alpha slots into the shard. The inert index
            # must be OUT OF RANGE (n_loc), not -1: mode="drop" only drops
            # beyond-range indices, while -1 wraps to the shard's LAST row
            # and would erase its alpha on every round.
            l_scatter = jnp.where(own, l, jnp.int32(n_loc))
            alpha = st.alpha.at[l_scatter].set(
                jnp.where(own, alpha_w, 0.0), mode="drop")
            return BlockState(alpha, f, b_hi, b_lo,
                              st.pairs + t, st.rounds + 1, f_err)

        return lax.while_loop(cond, body, state)

    shard = P(DATA_AXIS)
    rep = P()
    state_specs = BlockState(alpha=shard, f=shard, b_hi=rep, b_lo=rep,
                             pairs=rep, rounds=rep,
                             f_err=shard if compensated else None)
    mapped = mesh_shard_map(
        chunk_body,
        mesh=mesh,
        in_specs=(shard, shard, shard, shard, shard, state_specs, rep),
        out_specs=state_specs,
        check=False,  # while_loop carries defeat the replication checker
    )
    return _jit_runner(mapped, donate_state)


def make_block_shardlocal_chunk_runner(mesh: Mesh, kp: KernelParams, c,
                                       eps: float, tau: float, q: int,
                                       inner_iters: int,
                                       rounds_per_chunk: int,
                                       sync_rounds: int = 1,
                                       inner_impl: str = "xla",
                                       interpret: bool = False,
                                       selection: str = "mvp",
                                       compensated: bool = False,
                                       pair_batch: int = 1,
                                       donate_state: bool = False,
                                       ring_exchange: bool = False):
    """SHARD-PARALLEL working sets (config.local_working_sets — the
    Cascade-SVM / partitioned-parallel-SMO structure re-derived for the
    mesh; Graf et al. NIPS 2004, Cao et al. IEEE TNN 2006, PAPERS.md):
    instead of every chip replicating ONE global q-sized subproblem
    chain per round (make_block_chunk_runner — the Amdahl term that caps
    docs/SCALING.md's covtype P=8 projection at 1.3x), every chip
    selects a q-sized working set FROM ITS OWN SHARD, builds its (q, q)
    Gram fully locally (working rows ARE local rows), and runs its own
    subproblem chain concurrently with all other chips — P different
    chains in the same wall-clock, so useful pairs per round scale ~P.

    One LOCAL round, per device (zero collectives — the whole point):

      1. local masked selection over the shard's rows (the single-chip
         select_block; no _global_top all_gather);
      2. local gathers + local (q, q) Gram + the subproblem chain;
      3. local fold f_loc += coef @ K(W_loc, shard) and local alpha
         scatter (working rows are owned rows — the disjoint-row
         regime: shards can never write the same alpha).

    Every `sync_rounds` (R) local rounds, one SYNC:

      4. ONE all_gather of the window's (R*q, d+3) touched-row blocks
         [x row | x_sq | fold coef | pair-count lane] — the only bulk
         collective; each shard folds the OTHER shards' P-1 blocks into
         its local gradient with (R*q, d) x (d, n_loc) kernel-row
         matmuls (its own block was already folded locally each round
         and is skipped by rotation — fp grouping differs per shard
         but f is shard-local state);
      5. the exact global KKT stopping pair from the corrected f: local
         masked extrema (ops/select.py stopping_extrema) + ONE (2,) max
         allreduce handoff. b_hi/b_lo therefore have the SAME semantics
         as every other block engine's carry — exact extrema of the
         post-fold gradient, never of a stale view.

    Staleness contract (the pair_batch/pipelined discipline, lifted from
    pairs/rounds to shards): each shard's SELECTION ranks violators by a
    gradient that is stale w.r.t. other shards' concurrent updates, but
    every EXECUTED update is exact on the shard's own view — own-row
    alpha is always current (disjoint rows), the subproblem maintains
    f_W incrementally from its own updates, and cross-shard
    contributions enter f only through the sync fold, after which the
    next window re-ranks from the corrected gradient (the
    candidate_live_mask role is played by the selection masks
    themselves: they re-derive I_up/I_low membership from the CURRENT
    own-shard alpha every round, so a slot can never go stale the way a
    prefetched cross-round candidate can). Wrong-priority work burns
    rounds, not correctness. Because per-shard working sets can starve
    near the optimum (the global violating pair may need rows from two
    shards, which no local chain can pair), final convergence is owned
    by the ENDGAME DEMOTION in solve_mesh: when the global gap stops
    halving across a sync window or falls below 10*eps, the host drops
    back to the exact global-working-set runner — so parity artifacts
    and final-ulp convergence are unaffected, and this engine is purely
    a bulk-phase accelerator.

    Budget semantics: each shard clamps its own window spend to the
    replicated remaining budget, but P shards spend concurrently, so
    `pairs` may overshoot max_iter by up to (P-1) * R * inner_iters —
    the reason config validation refuses budget_mode (which promises an
    EXACT pair count) for this engine.

    Collectives per sync: 2 dispatches (one all_gather of
    P * R*q * (d+3) f32 + one (2,) f32 allreduce) for up to P*R*inner
    executed pairs — vs the global runner's 3 dispatches per round for
    `inner` pairs: dispatches per pair drop ~3PR/2 (>= P for any R).
    Payload BYTES per pair drop only ~(2P+d+5)/(d+3) (rows must travel
    exactly once either way) — see docs/SCALING.md round-7 for the
    honest accounting.

    Feature kernels, selection in {mvp, second_order} (config
    validates). Bit-exact reduction: local_working_sets=1 routes to
    make_block_chunk_runner in solve_mesh — this runner never runs.
    """
    if kp.kind == "precomputed":
        raise ValueError(
            "shard-local working sets support feature kernels only (a "
            "precomputed Gram's sync fold would need global column ids "
            "for rows the shard does not own; use the plain runner)")
    if selection not in ("mvp", "second_order"):
        raise ValueError(
            "shard-local working sets support selection in {'mvp', "
            "'second_order'} (the nu rule's per-class stopping pair "
            "does not reduce shard-locally; see ops/select.py "
            "stopping_extrema)")
    _check_ring(ring_exchange, mesh, kp, selection)
    p_dev = int(mesh.devices.size)
    r_sync = int(sync_rounds)

    def chunk_body(x_loc, y_loc, x_sq_loc, k_diag_loc, valid_loc,
                   state: BlockState, max_iter):
        n_loc, d = x_loc.shape
        end = state.rounds + rounds_per_chunk
        dev = lax.axis_index(DATA_AXIS).astype(jnp.int32)

        def cond(st: BlockState):
            return ((st.rounds < end) & (st.pairs < max_iter)
                    & (st.b_lo > st.b_hi + 2.0 * eps))

        def window(st: BlockState):
            pend0 = jnp.zeros((r_sync, q, d + 3), jnp.float32)

            def local_round(r, carry):
                alpha, f, f_err, pend, t_tot = carry
                # The SAME round body the single-chip engine compiles
                # (solver/block.py run_local_round), on the shard views:
                # selection, Gram, subproblem, own-delta fold, scatter —
                # all local, zero collectives. The returned extrema are
                # the shard-LOCAL pair; they gate this shard's budget
                # (a shard whose local gap closed idles the round) and
                # are otherwise discarded — the global stopping pair is
                # computed at the sync from the corrected gradient.
                alpha, f, f_err, _, _, t, coef, qx, qsq = run_local_round(
                    x_loc, y_loc, x_sq_loc, k_diag_loc, valid_loc,
                    alpha, f, f_err, max_iter - st.pairs - t_tot,
                    kp, c, eps, tau, q, inner_iters, inner_impl,
                    interpret, selection, pair_batch=pair_batch)
                # Record the round's touched block for the sync fold.
                # Dead slots carry coef 0 (their filler rows are real
                # rows, so the gathered block stays finite); lane d+2
                # smuggles the round's pair count in slot 0 so the
                # replicated global counter rides the SAME all_gather
                # (exact: integer-valued f32 well under 2^24).
                tcol = jnp.zeros((q,), jnp.float32).at[0].set(
                    t.astype(jnp.float32))
                blk = jnp.concatenate(
                    [qx.astype(jnp.float32), qsq[:, None],
                     coef[:, None], tcol[:, None]], axis=1)
                return alpha, f, f_err, pend.at[r].set(blk), t_tot + t

            alpha, f, f_err, pend, _ = lax.fori_loop(
                0, r_sync, local_round,
                (st.alpha, st.f, st.f_err, pend0, jnp.int32(0)))

            if ring_exchange:
                # ---- SYNC over the ICI ring (ops/ring.py): the
                # window's blocks travel P-1 remote-DMA hops and every
                # arriving hop is folded IN-KERNEL — same rotation
                # order, same kahan step, bit-identical gradient — so
                # the sync's device form has zero XLA collectives left
                # except the stopping handoff below (tpulint
                # `shardlocal_chunk_ring` pins it).
                from dpsvm_tpu.ops.ring import ring_fold_window

                with jax.named_scope("mesh_sync_ring"):
                    ag, f, f_err = ring_fold_window(
                        pend.reshape(r_sync * q, d + 3), x_loc,
                        x_sq_loc, f, f_err, kp, p_dev,
                        compensated=f_err is not None,
                        interpret=interpret)
                pairs = st.pairs + jnp.sum(
                    ag[:, :, d + 2]).astype(jnp.int32)
            else:
                # ---- SYNC: the window's ONLY collectives.
                with jax.named_scope("mesh_sync"):
                    ag = lax.all_gather(pend.reshape(r_sync * q, d + 3),
                                        DATA_AXIS)  # (P, R*q, d+3)
                pairs = st.pairs + jnp.sum(
                    ag[:, :, d + 2]).astype(jnp.int32)

                # Cross-shard fold: one (R*q, n_loc) kernel-row fold
                # per PEER block — the same per-step footprint as R
                # plain rounds' folds. The rotation skips the own block
                # entirely (its deltas were folded locally each round;
                # a masked all-P loop would burn one full fold matmul
                # on zeros).
                def fold_one(i, carry):
                    f, f_err = carry
                    blk = ag[(dev + 1 + i) % p_dev]
                    delta = blk[:, d + 1] @ kernel_rows(
                        x_loc, x_sq_loc, blk[:, :d].astype(x_loc.dtype),
                        blk[:, d], kp)
                    return maybe_kahan(f, f_err, delta)

                f, f_err = lax.fori_loop(0, p_dev - 1, fold_one,
                                         (f, f_err))

            # ---- global stopping pair from the CORRECTED gradient:
            # local masked extrema + one (2,) max-allreduce handoff.
            f_eff = f if f_err is None else f - f_err
            bh_l, bl_l = stopping_extrema(f_eff, alpha, y_loc, c,
                                          valid=valid_loc, rule=selection)
            g = lax.pmax(jnp.stack([-bh_l, bl_l]), DATA_AXIS)
            return BlockState(alpha, f, -g[0], g[1], pairs,
                              st.rounds + r_sync, f_err)

        return lax.while_loop(cond, window, state)

    shard = P(DATA_AXIS)
    rep = P()
    state_specs = BlockState(alpha=shard, f=shard, b_hi=rep, b_lo=rep,
                             pairs=rep, rounds=rep,
                             f_err=shard if compensated else None)
    mapped = mesh_shard_map(
        chunk_body,
        mesh=mesh,
        in_specs=(shard, shard, shard, shard, shard, state_specs, rep),
        out_specs=state_specs,
        check=False,  # while_loop carries defeat the replication checker
    )
    return _jit_runner(mapped, donate_state)


def make_block_pipelined_chunk_runner(mesh: Mesh, kp: KernelParams, c,
                                      eps: float, tau: float, q: int,
                                      inner_iters: int,
                                      rounds_per_chunk: int,
                                      inner_impl: str = "xla",
                                      interpret: bool = False,
                                      selection: str = "mvp",
                                      compensated: bool = False,
                                      pair_batch: int = 1,
                                      donate_state: bool = False,
                                      ring_exchange: bool = False):
    """PIPELINED mesh block runner (config.pipeline_rounds — the mesh
    counterpart of solver/block.py run_chunk_block_pipelined, and the
    path where the overlap is STRUCTURAL rather than scheduler luck):
    the next round's distributed selection (all_gather of per-shard
    candidates) and working-set recovery (the (q, d+3) masked psum — the
    round's only bulk collective) are issued from the PRE-fold carry, so
    they have no data dependence on the current round's replicated
    subproblem chain and XLA's async collectives can run them UNDER it.
    docs/SCALING.md carries exactly these two terms (t_ici plus the
    selection share of the a-floor) as the per-round latency that shrinks
    with neither P nor n — this engine is the remedy VERDICT round-5
    ranked as item 3.

    What stays on the critical path: ONE tiny handoff psum per round —
    the (q, 2) replication of the staged working set's CURRENT
    [alpha, f] (those change under the in-flight round, so they cannot
    be prefetched; x rows / x_sq / k_diag / y are static and prefetch
    EXACTLY regardless of selection staleness) — then the replicated
    subproblem, the purely local fold, and the owned-slot scatter.
    Staleness/exactness contract is run_chunk_block_pipelined's: stale
    SELECTION, exact UPDATES via the handoff's corrected-gradient
    re-rank + candidate_live_mask gating; a zero-progress round folds a
    zero delta so the next prefetch reads the unchanged (exact) gradient
    — stale selection wastes at most one round, never cycles.

    Feature kernels only (a precomputed Gram's symmetric-gather round is
    already collective-light — its kb psum is (q, q); use the plain
    runner there). selection in {mvp, second_order}.
    """
    if kp.kind == "precomputed":
        raise ValueError(
            "pipelined mesh rounds support feature kernels only (the "
            "precomputed Gram's symmetric round has no (q, d) psum to "
            "hide; use make_block_chunk_runner)")
    _check_ring(ring_exchange, mesh, kp, selection)

    def chunk_body(x_loc, y_loc, x_sq_loc, k_diag_loc, valid_loc,
                   state: BlockState, max_iter):
        n_loc = x_loc.shape[0]
        end = state.rounds + rounds_per_chunk
        # Static per-row scalars: pure functions of the data, so the
        # prefetched values are exact no matter how stale the selection.
        stat_loc = jnp.stack([x_sq_loc, k_diag_loc, y_loc], axis=1)

        def prefetch(f_eff, alpha):
            """Next working set + its data-side artifacts from the
            pre-fold (f, alpha). All collectives here are overlappable:
            nothing downstream of the in-flight subproblem feeds them.
            Under ring_exchange the candidate gather + row psum become
            ONE DMA-ring pass carrying rows and static scalars with the
            candidates (_select_block_mesh_ring) — the overlap then no
            longer depends on XLA scheduling async collectives under
            the subproblem chain. The (q, 2) handoff psum stays: it
            reads per-slot alpha/f CURRENT at round entry, which no
            prefetch can carry."""
            if ring_exchange:
                d_feat = x_loc.shape[1]
                data_loc = jnp.concatenate(
                    [x_loc.astype(jnp.float32), stat_loc], axis=1)
                w, ok, b_hi, b_lo, wdata = _select_block_mesh_ring(
                    f_eff, alpha, y_loc, valid_loc, c, q, data_loc,
                    int(mesh.devices.size), interpret)
                qx, stat = wdata[:, :d_feat], wdata[:, d_feat:]
            else:
                w, ok, b_hi, b_lo = _select_block_mesh(
                    f_eff, alpha, y_loc, valid_loc, c, q, rule=selection)
                qx, stat, _, _ = _gather_ws(x_loc, stat_loc, w, ok,
                                            n_loc)
            qsq, kd, y_w = stat[:, 0], stat[:, 1], stat[:, 2]
            dots = jnp.dot(qx, qx.T, preferred_element_type=jnp.float32)
            kb = kernel_from_dots(dots, qsq, qsq, kp)
            return (w, ok, qx, qsq, kb, kd, y_w), b_hi, b_lo

        cand0, bhi0, blo0 = prefetch(eff_f(state), state.alpha)
        st0 = state._replace(b_hi=bhi0, b_lo=blo0)

        def cond(carry):
            st, _ = carry
            return ((st.rounds < end) & (st.pairs < max_iter)
                    & (st.b_lo > st.b_hi + 2.0 * eps))

        def body(carry):
            st, cand = carry
            w, slot_ok0, qx, qsq, kb_w, kd_w, y_w = cand
            f_cur = eff_f(st)
            # ---- handoff: ONE (q, 2) psum replicates the staged W's
            # CURRENT per-slot alpha/f, then the corrected-gradient
            # gating masks slots the previous round saturated.
            l, own, l_safe = _ws_owners(w, slot_ok0, n_loc)
            with jax.named_scope("mesh_handoff"):
                dyn = _psum_scal(jnp.stack([st.alpha, f_cur], axis=1),
                                 own, l_safe)
            a_w0, f_w0 = dyn[:, 0], dyn[:, 1]
            slot_ok = slot_ok0 & candidate_live_mask(a_w0, y_w, c)
            # No gap gate on `limit`: cond() guarantees the carried gap
            # is open on body entry (see run_chunk_block_pipelined).
            limit = jnp.minimum(jnp.int32(inner_iters),
                                max_iter - st.pairs)
            if inner_impl == "pallas":
                from dpsvm_tpu.ops.pallas_subproblem import (
                    solve_subproblem_pallas)

                alpha_w, t = solve_subproblem_pallas(
                    kb_w, a_w0, y_w, f_w0, kd_w,
                    slot_ok.astype(jnp.float32), limit, c, eps, tau,
                    rule=selection, interpret=interpret,
                    pair_batch=pair_batch)
            else:
                alpha_w, _, t = _solve_subproblem(
                    kb_w, kd_w, slot_ok, a_w0, y_w, f_w0, c, eps, tau,
                    limit, rule=selection, pair_batch=pair_batch)
            coef = jnp.where(slot_ok, (alpha_w - a_w0) * y_w, 0.0)
            # ---- next prefetch from the PRE-fold carry: its all_gather
            # + row psum never wait on the subproblem above.
            nxt, bhi_n, blo_n = prefetch(f_cur, st.alpha)
            # ---- purely local fold + owned-slot scatter.
            k_rows_loc = kernel_rows(x_loc, x_sq_loc,
                                     qx.astype(x_loc.dtype), qsq, kp)
            f, f_err = maybe_kahan(st.f, st.f_err, coef @ k_rows_loc)
            own_live = own & slot_ok
            l_scatter = jnp.where(own_live, l, jnp.int32(n_loc))
            alpha = st.alpha.at[l_scatter].set(
                jnp.where(own_live, alpha_w, 0.0), mode="drop")
            new_st = BlockState(alpha, f, bhi_n, blo_n, st.pairs + t,
                                st.rounds + 1, f_err)
            return new_st, nxt

        final, _ = lax.while_loop(cond, body, (st0, cand0))
        return final

    shard = P(DATA_AXIS)
    rep = P()
    state_specs = BlockState(alpha=shard, f=shard, b_hi=rep, b_lo=rep,
                             pairs=rep, rounds=rep,
                             f_err=shard if compensated else None)
    mapped = mesh_shard_map(
        chunk_body,
        mesh=mesh,
        in_specs=(shard, shard, shard, shard, shard, state_specs, rep),
        out_specs=state_specs,
        check=False,  # while_loop carries defeat the replication checker
    )
    return _jit_runner(mapped, donate_state)


def _global_top_from_rows(upv, upi, lov, loi, h: int):
    """Replicated global working set from per-shard PER-ROW candidates
    (the fused fold+select kernel's outputs, ids already globalized):
    exact local top-h per side, one all_gather, exact global top-h,
    shared cross-half dedup. The gathered union contains each shard's
    true extremum, so the global MVP invariant and the (b_hi, b_lo)
    extrema are exact — same argument as _global_top, with the fused
    kernel replacing the masked-score approx_max_k stage."""
    scores = jnp.stack([-upv, lov])  # (2, r)
    ids = jnp.stack([upi, loi])
    v, i = lax.top_k(scores, h)
    g = jnp.take_along_axis(ids, i, axis=1)
    av = lax.all_gather(v, DATA_AXIS)  # (P, 2, h)
    ag = lax.all_gather(g, DATA_AXIS)
    av = jnp.moveaxis(av, 0, 1).reshape(2, -1)
    ag = jnp.moveaxis(ag, 0, 1).reshape(2, -1)
    gv, gi = lax.top_k(av, h)
    gids = jnp.take_along_axis(ag, gi, axis=1)
    w, slot_ok = combine_halves(gids[0], jnp.isfinite(gv[0]),
                                gids[1], jnp.isfinite(gv[1]))
    return w, slot_ok, -gv[0, 0], gv[1, 0]


def make_block_fused_chunk_runner(mesh: Mesh, kp: KernelParams, c,
                                  eps: float, tau: float, q: int,
                                  inner_iters: int, rounds_per_chunk: int,
                                  inner_impl: str = "pallas",
                                  interpret: bool = False,
                                  selection: str = "mvp",
                                  compensated: bool = False,
                                  pair_batch: int = 1,
                                  donate_state: bool = False):
    """Fused-fold mesh block runner: each shard's fold and per-row
    candidate selection run as ONE Pallas pass over its f shard
    (ops/pallas_fold_select.py — the mesh counterpart of solver/block.py
    run_chunk_block_fused), then one all_gather assembles the exact
    global working set. This removes the separate full-n_loc
    mask+approx_max_k stage from every shard's round chain — the regime
    where it pays is big n_loc (solver/smo.py measured the single-chip
    crossover at ~200k rows), i.e. exactly the big-n·d pod story of
    docs/SCALING.md.

    Requires: n_loc padded to a multiple of 1024 (solve_mesh pads via
    pad_rows(multiple=1024)), q/2 <= n_loc/128, selection in
    {mvp, second_order}, feature kernels.
    """
    from dpsvm_tpu.ops.pallas_fold_select import fold_select

    def chunk_body(x_loc, y_loc, x_sq_loc, k_diag_loc, valid_loc,
                   state: BlockState, max_iter):
        n_loc = x_loc.shape[0]
        rows = n_loc // 128
        shp = (rows, 128)
        h = q // 2
        y2d = y_loc.reshape(shp)
        valid2d = valid_loc.astype(jnp.float32).reshape(shp)
        end = state.rounds + rounds_per_chunk
        comp = state.f_err is not None
        dev_off = lax.axis_index(DATA_AXIS).astype(jnp.int32) * n_loc

        # Seed candidates once per chunk (amortized over the rounds).
        w0, ok0, bhi0, blo0 = _select_block_mesh(
            eff_f(state), state.alpha, y_loc, valid_loc, c, q,
            rule=selection)
        st0 = state._replace(b_hi=bhi0, b_lo=blo0)

        def cond(carry):
            st, w, ok = carry
            return ((st.rounds < end) & (st.pairs < max_iter)
                    & (st.b_lo > st.b_hi + 2.0 * eps))

        def body(carry):
            st, w, slot_ok = carry
            f_cur = eff_f(st)
            scal_loc = jnp.stack(
                [x_sq_loc, k_diag_loc, st.alpha, y_loc, f_cur], axis=1)
            alpha_w, coef, t, l, own, k_rows_loc = _mesh_round_core(
                x_loc, x_sq_loc, scal_loc, w, slot_ok,
                st.b_lo > st.b_hi + 2.0 * eps, max_iter - st.pairs,
                kp, c, eps, tau, inner_iters, inner_impl, interpret,
                selection, pair_batch=pair_batch)
            delta2d = (coef @ k_rows_loc).reshape(shp)
            # Scatter owned alpha BEFORE the fused pass (its masks must
            # see updated box membership).
            l_scatter = jnp.where(own, l, jnp.int32(n_loc))
            alpha = st.alpha.at[l_scatter].set(
                jnp.where(own, alpha_w, 0.0), mode="drop")
            err2d = st.f_err.reshape(shp) if comp else None
            f2d, err_new2d, upv, upi, lov, loi = fold_select(
                st.f.reshape(shp), err2d, alpha.reshape(shp), y2d,
                valid2d, delta2d, c, compensated=comp,
                interpret=interpret)
            # Candidate ids are shard-local flat ids; globalize. (Rows
            # with empty candidate sets carry +-inf values and an
            # arbitrary real local id — masked downstream by the
            # isfinite check, so the offset add is always safe.)
            w_n, ok_n, bhi_n, blo_n = _global_top_from_rows(
                upv, upi + dev_off, lov, loi + dev_off, h)
            new_st = BlockState(
                alpha, f2d.reshape(n_loc), bhi_n, blo_n, st.pairs + t,
                st.rounds + 1,
                err_new2d.reshape(n_loc) if comp else None)
            return new_st, w_n, ok_n

        final, _, _ = lax.while_loop(cond, body, (st0, w0, ok0))
        return final

    shard = P(DATA_AXIS)
    rep = P()
    state_specs = BlockState(alpha=shard, f=shard, b_hi=rep, b_lo=rep,
                             pairs=rep, rounds=rep,
                             f_err=shard if compensated else None)
    mapped = mesh_shard_map(
        chunk_body,
        mesh=mesh,
        in_specs=(shard, shard, shard, shard, shard, state_specs, rep),
        out_specs=state_specs,
        check=False,  # while_loop carries defeat the replication checker
    )
    return _jit_runner(mapped, donate_state)


def make_block_active_chunk_runner(mesh: Mesh, kp: KernelParams, c,
                                   eps: float, tau: float, q: int,
                                   inner_iters: int, rounds_per_chunk: int,
                                   m: int, k_rounds: int,
                                   inner_impl: str = "xla",
                                   interpret: bool = False,
                                   selection: str = "mvp",
                                   compensated: bool = False,
                                   pair_batch: int = 1,
                                   donate_state: bool = False):
    """Active-set ("shrinking") variant of make_block_chunk_runner — the
    mesh port of solver/block.py run_chunk_block_active (the layer the
    reference scales with MPI ranks, svmTrainMain.cpp:244). One CYCLE:

      1. ONE distributed active selection: the m globally most-violating
         rows (_select_block_mesh with q=m), which also yields the exact
         global stopping extrema; the winning ids are REPLICATED on every
         device;
      2. one (m, d+5) masked psum replicates the active rows' features
         and per-row scalars (x, x_sq, k_diag, alpha, y, f);
      3. up to `k_rounds` block rounds run on the REPLICATED (m,)-sized
         active state — every device executes the identical subproblem
         and active fold (the reference's replicated-update trick,
         svmTrainMain.cpp:285-299, lifted from one pair to the whole
         cycle), so the inner rounds need ZERO collectives: the round
         cadence is no longer bounded by all_gather/psum latency, which
         is exactly what shrinking must fix on a pod (per-round exchange
         was the mesh block engine's latency floor);
      4. one batched reconciliation fold applies the cycle's accumulated
         (slot, coef) deltas to the SHARDED gradient with a purely local
         (k_rounds*q, n_loc) kernel-row matmul, then each shard scatters
         back the active rows it owns.

    Exactness mirrors run_chunk_block_active: f is linear in the round
    coefs so deferring non-active rows' folds changes fp grouping only;
    convergence is only declared from step 1's full-f extrema. Replicated
    inner compute is deterministic, so every device carries bit-identical
    active state. Requires q <= m and m/2 (m/4 under nu) candidates per
    shard, i.e. m <= gran * n_loc (solve_mesh clamps).
    """

    def chunk_body(x_loc, y_loc, x_sq_loc, k_diag_loc, valid_loc,
                   state: BlockState, max_iter):
        n_loc = x_loc.shape[0]
        end = state.rounds + rounds_per_chunk

        def cond(st: BlockState):
            return ((st.rounds < end) & (st.pairs < max_iter)
                    & (st.b_lo > st.b_hi + 2.0 * eps))

        def cycle(st: BlockState):
            f_cur = eff_f(st)
            act_ids, act_ok, b_hi, b_lo = _select_block_mesh(
                f_cur, st.alpha, y_loc, valid_loc, c, m, rule=selection)
            gap_open = b_lo > b_hi + 2.0 * eps
            scal_loc = jnp.stack(
                [x_sq_loc, k_diag_loc, st.alpha, y_loc, f_cur], axis=1)
            x_act, scal, l_act, own_act = _gather_ws(
                x_loc, scal_loc, act_ids, act_ok, n_loc)
            sq_act, kd_act, a_act0, y_act, f_act0 = (
                scal[:, 0], scal[:, 1], scal[:, 2], scal[:, 3], scal[:, 4])
            x_act = x_act.astype(x_loc.dtype)
            pend_w0 = jnp.zeros((k_rounds, q), jnp.int32)
            pend_c0 = jnp.zeros((k_rounds, q), jnp.float32)

            def inner_cond(carry):
                _, _, _, _, k, t_tot, open_a = carry
                return ((k < k_rounds) & open_a
                        & (st.pairs + t_tot < max_iter))

            def inner_body(carry):
                a_act, f_act, pend_w, pend_c, k, t_tot, _ = carry
                # The shared single-chip round step, on the replicated
                # active views (valid=act_ok masks dead filler slots).
                w, slot_ok, bh_a, bl_a, a_w, coef, t, qx, qsq = _round_core(
                    x_act, y_act, sq_act, kd_act, f_act, a_act, act_ok,
                    max_iter - st.pairs - t_tot,
                    kp, c, eps, tau, q, inner_iters, inner_impl, interpret,
                    selection, pair_batch=pair_batch)
                open_a = bl_a > bh_a + 2.0 * eps
                k_rows_act = kernel_rows(x_act, sq_act, qx, qsq, kp)
                f_act = f_act + coef @ k_rows_act
                safe_w = jnp.where(slot_ok, w, jnp.int32(m))
                a_act = a_act.at[safe_w].set(
                    jnp.where(slot_ok, a_w, 0.0), mode="drop")
                # Deltas recorded by ACTIVE-SLOT index (the reconciliation
                # fold reads features from the replicated x_act, not the
                # full x as the single-chip engine does).
                pend_w = pend_w.at[k].set(w)
                pend_c = pend_c.at[k].set(coef)
                return a_act, f_act, pend_w, pend_c, k + 1, t_tot + t, open_a

            a_act, f_act, pend_w, pend_c, k_done, t_tot, _ = lax.while_loop(
                inner_cond, inner_body,
                (a_act0, f_act0, pend_w0, pend_c0, jnp.int32(0),
                 jnp.int32(0), gap_open))

            # Reconciliation: one LOCAL batched fold of the cycle's deltas
            # into the shard's gradient (dead slots carry coef 0).
            def do_fold(carry):
                f, err = carry
                wf = pend_w.reshape(-1)
                cf = pend_c.reshape(-1)
                xw = jnp.take(x_act, wf, axis=0)  # (k_rounds*q, d)
                sqw = jnp.take(sq_act, wf)
                delta = cf @ kernel_rows(x_loc, x_sq_loc, xw, sqw, kp)
                return maybe_kahan(f, err, delta)

            f, f_err = lax.cond(t_tot > 0, do_fold, lambda c: c,
                                (st.f, st.f_err))
            # Scatter back the active rows THIS shard owns: the
            # incrementally-maintained replicated values overwrite the
            # fold's regrouped results so all views agree exactly (see
            # run_chunk_block_active). Only live owned slots scatter.
            l_scatter = jnp.where(own_act, l_act, jnp.int32(n_loc))
            f = f.at[l_scatter].set(
                jnp.where(own_act, f_act, 0.0), mode="drop")
            if f_err is not None:
                # Scattered entries were reset directly; their residual
                # no longer describes them (see run_chunk_block_active).
                f_err = f_err.at[l_scatter].set(0.0, mode="drop")
            alpha = st.alpha.at[l_scatter].set(
                jnp.where(own_act, a_act, 0.0), mode="drop")
            return BlockState(alpha, f, b_hi, b_lo,
                              st.pairs + t_tot, st.rounds + k_done, f_err)

        return lax.while_loop(cond, cycle, state)

    shard = P(DATA_AXIS)
    rep = P()
    state_specs = BlockState(alpha=shard, f=shard, b_hi=rep, b_lo=rep,
                             pairs=rep, rounds=rep,
                             f_err=shard if compensated else None)
    mapped = mesh_shard_map(
        chunk_body,
        mesh=mesh,
        in_specs=(shard, shard, shard, shard, shard, state_specs, rep),
        out_specs=state_specs,
        check=False,  # while_loop carries defeat the replication checker
    )
    return _jit_runner(mapped, donate_state)


def make_ooc_mesh_programs(mesh: Mesh, kp: KernelParams, c, q: int,
                           n_loc: int, tile: int, selection: str = "mvp",
                           compensated: bool = False):
    """The per-device OOC TILE STREAM's device programs (ISSUE 19):
    solve_ooc_mesh (solver/ooc.py) drives these four jitted shard_maps
    while the host feeds every device its row shard's tiles.

    Row layout: device k owns global rows [k*n_loc, (k+1)*n_loc) —
    n_loc = tile * ceil(n / (P*tile)), so every shard is a whole number
    of stream tiles and stream step j carries each device's tile j as
    one (P*tile, d) host block put with a row-sharded NamedSharding.

    Collective budget per ROUND (the ``ooc_mesh_fold`` tpulint
    manifest pins it): selection's candidate all_gather pair plus ONE
    (q, 5) psum of the working-set scalars — and the FOLD has ZERO
    collectives (each device folds only its own rows; a stray per-tile
    collective is exactly the regression the budget DRIFTs on). The
    (q, q) subproblem itself runs replicated OUTSIDE these programs
    (solver/ooc.py _ooc_mesh_subproblem — the host round-trips its
    working-set rows anyway).

    Bitwise equality with the single-chip ooc trajectory (tests/
    test_ooc.py pins it at 2 devices): the fold traces the SAME
    ops/ooc.py fold_tile_body op sequence at the same (tile,) shapes,
    each gradient lane is updated exactly once per round (cross-tile
    order is irrelevant), the scalar psum gathers exactly one nonzero
    f32 term per slot (exact), and _select_block_mesh's device-major
    gather + exact top_k merge preserves select_block's tie-break.

    Returns dict(select=..., fold=..., scatter=..., norms=...):
      select(f, f_err?, alpha, y, x_sq, k_diag, valid)
          -> (w, slot_ok, b_hi, b_lo, scal (q, 5)) — all replicated;
          scal columns are [x_sq, k_diag, alpha, y, f_eff] at W.
      fold(x_blk, x_sq, f, f_err?, qx, qsq, coef, j)
          -> f[, f_err] — stream step j's local fold, f/f_err donated.
      scatter(alpha, w, slot_ok, a_w) -> alpha — owned slots only
          (inert index n_loc, the at[].set mode="drop" idiom), donated.
      norms(x_blk, x_sq, j) -> x_sq — setup-stream squared norms,
          computed ON DEVICE per (tile, d) block (the same jitted
          reduction shape as the single-chip setup pass, which is what
          makes x_sq — and everything downstream — bit-identical).
    """
    from dpsvm_tpu.ops.kernels import squared_norms
    from dpsvm_tpu.ops.ooc import fold_tile_body

    shard = P(DATA_AXIS)
    rep = P()

    def _select_core(f_cur, alpha_loc, y_loc, x_sq_loc, k_diag_loc,
                     valid_loc):
        w, slot_ok, b_hi, b_lo = _select_block_mesh(
            f_cur, alpha_loc, y_loc, valid_loc, c, q, rule=selection)
        _, own, l_safe = _ws_owners(w, slot_ok, n_loc)
        scal_loc = jnp.stack([x_sq_loc, k_diag_loc, alpha_loc, y_loc,
                              f_cur], axis=1)
        scal = _psum_scal(scal_loc, own, l_safe)
        return w, slot_ok, b_hi, b_lo, scal

    if compensated:
        def _sel_body(f_loc, err_loc, alpha_loc, y_loc, x_sq_loc,
                      k_diag_loc, valid_loc):
            return _select_core(f_loc - err_loc, alpha_loc, y_loc,
                                x_sq_loc, k_diag_loc, valid_loc)
        sel_in = (shard,) * 7
    else:
        def _sel_body(f_loc, alpha_loc, y_loc, x_sq_loc, k_diag_loc,
                      valid_loc):
            return _select_core(f_loc, alpha_loc, y_loc, x_sq_loc,
                                k_diag_loc, valid_loc)
        sel_in = (shard,) * 6
    select = jax.jit(mesh_shard_map(
        _sel_body, mesh=mesh, in_specs=sel_in,
        out_specs=(rep, rep, rep, rep, rep), check=False))

    if compensated:
        def _fold_body(x_blk, x_sq_loc, f_loc, err_loc, qx, qsq, coef,
                       j):
            s = j * tile
            f_t = lax.dynamic_slice(f_loc, (s,), (tile,))
            e_t = lax.dynamic_slice(err_loc, (s,), (tile,))
            xsq_t = lax.dynamic_slice(x_sq_loc, (s,), (tile,))
            f_n, e_n, _ = fold_tile_body(x_blk, xsq_t, f_t, e_t, qx,
                                         qsq, coef, kp,
                                         want_dots=False,
                                         compensated=True)
            return (lax.dynamic_update_slice(f_loc, f_n, (s,)),
                    lax.dynamic_update_slice(err_loc, e_n, (s,)))
        fold = jax.jit(mesh_shard_map(
            _fold_body, mesh=mesh,
            in_specs=(shard, shard, shard, shard, rep, rep, rep, rep),
            out_specs=(shard, shard), check=False),
            donate_argnums=(2, 3))
    else:
        def _fold_body(x_blk, x_sq_loc, f_loc, qx, qsq, coef, j):
            s = j * tile
            f_t = lax.dynamic_slice(f_loc, (s,), (tile,))
            xsq_t = lax.dynamic_slice(x_sq_loc, (s,), (tile,))
            f_n, _, _ = fold_tile_body(x_blk, xsq_t, f_t, None, qx,
                                       qsq, coef, kp, want_dots=False,
                                       compensated=False)
            return lax.dynamic_update_slice(f_loc, f_n, (s,))
        fold = jax.jit(mesh_shard_map(
            _fold_body, mesh=mesh,
            in_specs=(shard, shard, shard, rep, rep, rep, rep),
            out_specs=shard, check=False),
            donate_argnums=(2,))

    def _scatter_body(alpha_loc, w, slot_ok, a_w):
        l, own, _ = _ws_owners(w, slot_ok, n_loc)
        l_scatter = jnp.where(own, l, jnp.int32(n_loc))
        return alpha_loc.at[l_scatter].set(
            jnp.where(own, a_w, 0.0), mode="drop")
    scatter = jax.jit(mesh_shard_map(
        _scatter_body, mesh=mesh, in_specs=(shard, rep, rep, rep),
        out_specs=shard, check=False), donate_argnums=(0,))

    def _norms_body(x_blk, x_sq_loc, j):
        return lax.dynamic_update_slice(
            x_sq_loc, squared_norms(x_blk), (j * tile,))
    norms = jax.jit(mesh_shard_map(
        _norms_body, mesh=mesh, in_specs=(shard, shard, rep),
        out_specs=shard, check=False), donate_argnums=(1,))

    return dict(select=select, fold=fold, scatter=scatter, norms=norms)
