"""Device mesh construction and multi-host initialization.

Replaces the reference's launcher layer (mpirun + hostfile, Makefile:74 and
hf:1-11, plus the MPI Init/Get_rank/Barrier boilerplate in
svmTrainMain.cpp:144-198): on TPU the SPMD program is compiled once over a
``jax.sharding.Mesh`` and XLA inserts the collectives; there is no explicit
rank bookkeeping or barrier code anywhere in the solver.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh


DATA_AXIS = "data"


def mesh_shard_map(f, mesh: Mesh, in_specs, out_specs, check: bool = True):
    """``shard_map`` across jax versions — THE one place the API skew is
    absorbed (every shard_map call site routes through here). jax >=
    0.5 exposes ``jax.shard_map`` with the replication check named
    ``check_vma``; 0.4.x has only ``jax.experimental.shard_map`` with
    the same knob named ``check_rep``.

    ``check=False`` is for the solver chunk runners ONLY: their
    replicated-output claims (b_hi/b_lo/pairs) are true by construction
    (identical replicated compute) but the static checker cannot see
    that through while_loop carries. Everything else (prediction,
    smoke psums) keeps the check on so a broken replication claim fails
    at trace time instead of returning per-shard garbage."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check)
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=check)


def make_data_mesh(
    num_devices: Optional[int] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """1-D mesh over the `data` axis — the row-shard axis of SURVEY.md
    section 2.3 (one shard per reference MPI rank)."""
    if devices is None:
        devices = jax.devices()
    if num_devices is not None:
        if num_devices > len(devices):
            raise ValueError(
                f"requested {num_devices} devices, only {len(devices)} visible")
        devices = devices[:num_devices]
    return Mesh(np.asarray(devices), (DATA_AXIS,))


def initialize_multihost(coordinator_address: Optional[str] = None,
                         num_processes: Optional[int] = None,
                         process_id: Optional[int] = None) -> None:
    """Multi-host bring-up: the `mpirun --hostfile` equivalent.

    On a real pod slice each host runs the same program and calls this once
    before building the mesh; jax.distributed wires the DCN coordination
    that OpenMPI's ssh launcher provided for the reference.
    """
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def shard_padded_rows(mesh: Mesh, arr, multiple: int = 8):
    """Pad `arr`'s leading axis to a mesh-divisible, lane-friendly count
    (pad_rows) and device_put it row-sharded over the data axis.

    ONE definition of the "pad then shard rows" staging step, shared by
    mesh inference (predict.decision_function_mesh) and the serving
    engine's sharded SV union (serve.py). Pad rows are ZEROS and must be
    inert in the consumer (zero dual coefficients contribute nothing) —
    the same contract as the solver's padded rows."""
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    arr = np.asarray(arr)
    n = arr.shape[0]
    n_pad = pad_rows(n, mesh.size, multiple)
    if n_pad != n:
        padded = np.zeros((n_pad,) + arr.shape[1:], arr.dtype)
        padded[:n] = arr
        arr = padded
    return jax.device_put(jnp.asarray(arr),
                          NamedSharding(mesh, P(DATA_AXIS)))


def replicate_array(mesh: Mesh, arr):
    """device_put `arr` fully replicated over the mesh (PartitionSpec()).

    The companion of shard_padded_rows for the operands every shard
    reads whole — query batches and bias rows in the serving decision.
    ONE definition shared by serve.py's mesh staging and the v2
    engine's mesh union groups, so both feed the SAME cached executor
    with identically-placed operands."""
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    return jax.device_put(jnp.asarray(arr), NamedSharding(mesh, P()))


def pad_rows(n: int, num_shards: int, multiple: int = 8) -> int:
    """Padded row count: divisible by num_shards and a lane-friendly
    multiple. Replaces the reference's uneven ceil-sharding
    (initialize_shard_sizes, svmTrainMain.cpp:367-376), whose last shard
    can go non-positive (bug B3) — padded rows are masked out of selection
    instead."""
    per = -(-n // num_shards)
    per = -(-per // multiple) * multiple
    return per * num_shards
