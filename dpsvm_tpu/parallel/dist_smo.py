"""Distributed SMO over a device mesh (shard_map + XLA collectives).

TPU-native re-design of the reference's MPI layer (svmTrainMain.cpp):

* The reference row-partitions only the *compute* — every GPU holds a full
  replicated copy of X and alpha (svmTrain.cu:344,349) while f/y are shard
  local. Here EVERYTHING row-indexed is sharded over the ``data`` mesh
  axis — X, y, f, alpha, the cache lines — so memory scales with device
  count (SURVEY.md section 7.3 item 5); working-set rows are recovered
  with a masked ``psum`` instead of replication.
* The reference's per-iteration ``MPI_Allgather`` of 4 floats per rank,
  with working-set indices cast through float (bug B4,
  svmTrain.cu:478-479), becomes an ``all_gather`` of (float32 value,
  int32 index) candidate pairs inside the compiled loop — exact at any n.
* The redundant replicated global scan after the allgather
  (svmTrainMain.cpp:255-277) maps to the same min/max over the gathered
  (P,) vectors — O(P) work fused into the step, no host involvement.
* MPI barriers and rank bookkeeping disappear: the SPMD program is one
  XLA module; collectives ride ICI (and DCN between slices on multi-host).
* Shards are equal by construction — rows are padded to a multiple of the
  shard count and masked out of selection (fixes bug B3, the reference's
  possibly-non-positive last shard).

The per-iteration algebra is identical to the single-chip engine
(solver/smo.py); convergence trajectories match the single-chip run
iteration for iteration because tie-breaking is by global row index in
both.
"""

from __future__ import annotations

import time
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dpsvm_tpu.config import SVMConfig
from dpsvm_tpu.ops.kernels import KernelParams, kernel_diag, kernel_from_dots
from dpsvm_tpu.ops.select import c_of, low_mask, split_c, up_mask
from dpsvm_tpu.solver.cache import CacheState, init_cache, lookup_one, lookup_pair
from dpsvm_tpu.solver.result import SolveResult
from dpsvm_tpu.solver.smo import (SMOState, assert_finite_state,
                                  check_obs_finite, eff_f, kahan_add)
from dpsvm_tpu.parallel.mesh import (DATA_AXIS, make_data_mesh,
                                     mesh_shard_map, pad_rows)
from dpsvm_tpu.testing import faults

_I32_MAX = jnp.iinfo(jnp.int32).max

# Shard-local engine chunk bound when nothing else observes: the host's
# endgame-demotion check reads the gap at chunk boundaries, so chunks
# are capped at this many sync windows (the exact-runner tail after
# demotion runs the usual unobserved cadence). Small enough that a
# stalled engine is demoted promptly; large enough that the per-chunk
# host round-trip stays amortized over thousands of pair updates.
_SHARDLOCAL_WINDOWS_PER_CHUNK = 8

# One-time flag for _warn_multihost_retry_dropped: a k(k-1)/2-submodel
# multiclass job would otherwise repeat the identical warning per
# submodel solve (the nu-fallback warning discipline, PR 8).
_WARNED_MULTIHOST_RETRY = False


def _warn_multihost_retry_dropped(config) -> None:
    """Loud, once-per-process notice that retry_faults was dropped
    (ISSUE 13 satellite — the knob used to vanish silently): on a
    multi-host pod a faulted process cannot re-sync its peers'
    collectives mid-job, so in-process retries are impossible and the
    recovery procedure is a JOB RELAUNCH with ``--resume`` against the
    same ``--checkpoint`` path (process-0-written, backend-portable)."""
    global _WARNED_MULTIHOST_RETRY
    if _WARNED_MULTIHOST_RETRY or config.retry_faults <= 0:
        return
    _WARNED_MULTIHOST_RETRY = True
    import warnings

    warnings.warn(
        f"retry_faults={config.retry_faults} is disabled on this "
        f"{jax.process_count()}-process pod: a faulted process cannot "
        "re-sync its peers' collectives mid-job, so in-process retry "
        "cannot work multi-host. Recovery procedure: run with "
        "--checkpoint PATH --checkpoint-every N, and on a fault "
        "RELAUNCH the whole job with --resume — training continues "
        "from the last checkpoint.", stacklevel=3)


def _global_ids(n_loc: int) -> jax.Array:
    """Global row ids of this shard (contiguous row partitioning, like the
    reference's shard displacements, svmTrainMain.cpp:378-384)."""
    dev = lax.axis_index(DATA_AXIS)
    return dev.astype(jnp.int32) * n_loc + jnp.arange(n_loc, dtype=jnp.int32)


def _select_global(f, alpha, y, c, valid):
    """Distributed most-violating-pair selection.

    Local masked extrema -> all_gather of (value, index) candidates ->
    replicated global reduction with lowest-global-index tie-break. The
    semantic equivalent of reference step1 + Allgather + replicated scan
    (svmTrain.cu:469-481, svmTrainMain.cpp:244-277) fused into the
    compiled step.
    """
    cp, cn = split_c(c)
    n_loc = f.shape[0]
    gids = _global_ids(n_loc)
    up = up_mask(alpha, y, cp, cn) & valid
    low = low_mask(alpha, y, cp, cn) & valid
    f_up = jnp.where(up, f, jnp.inf)
    f_low = jnp.where(low, f, -jnp.inf)
    l_hi = jnp.argmin(f_up).astype(jnp.int32)
    l_lo = jnp.argmax(f_low).astype(jnp.int32)

    cand_vals = jnp.stack([f_up[l_hi], f_low[l_lo]])  # (2,) float32
    cand_idx = jnp.stack([gids[l_hi], gids[l_lo]])  # (2,) int32
    g_vals = lax.all_gather(cand_vals, DATA_AXIS)  # (P, 2)
    g_idx = lax.all_gather(cand_idx, DATA_AXIS)  # (P, 2)

    b_hi = jnp.min(g_vals[:, 0])
    i_hi = jnp.min(jnp.where(g_vals[:, 0] == b_hi, g_idx[:, 0], _I32_MAX))
    b_lo = jnp.max(g_vals[:, 1])
    i_lo = jnp.min(jnp.where(g_vals[:, 1] == b_lo, g_idx[:, 1], _I32_MAX))
    return i_hi, b_hi, i_lo, b_lo


def _select_global_nu(f, alpha, y, c, valid):
    """Distributed per-class most-violating-pair selection (the nu duals'
    Solver_NU rule; see ops/select.py select_working_set_nu). One
    all_gather of (4,) candidate values + (4,) int32 indices per
    iteration."""
    cp, cn = split_c(c)
    n_loc = f.shape[0]
    gids = _global_ids(n_loc)
    up = up_mask(alpha, y, cp, cn) & valid
    low = low_mask(alpha, y, cp, cn) & valid
    pos = y > 0

    def local_pair(cls):
        f_up = jnp.where(up & cls, f, jnp.inf)
        f_low = jnp.where(low & cls, f, -jnp.inf)
        l_hi = jnp.argmin(f_up).astype(jnp.int32)
        l_lo = jnp.argmax(f_low).astype(jnp.int32)
        return (f_up[l_hi], f_low[l_lo]), (gids[l_hi], gids[l_lo])

    (bh_p, bl_p), (ih_p, il_p) = local_pair(pos)
    (bh_n, bl_n), (ih_n, il_n) = local_pair(~pos)
    g_vals = lax.all_gather(jnp.stack([bh_p, bl_p, bh_n, bl_n]), DATA_AXIS)
    g_idx = lax.all_gather(jnp.stack([ih_p, il_p, ih_n, il_n]), DATA_AXIS)

    def reduce_col(col, take_min):
        v = g_vals[:, col]
        best = jnp.min(v) if take_min else jnp.max(v)
        idx = jnp.min(jnp.where(v == best, g_idx[:, col], _I32_MAX))
        return best, idx

    bh_p, ih_p = reduce_col(0, True)
    bl_p, il_p = reduce_col(1, False)
    bh_n, ih_n = reduce_col(2, True)
    bl_n, il_n = reduce_col(3, False)
    take_p = (bl_p - bh_p) >= (bl_n - bh_n)
    return (jnp.where(take_p, ih_p, ih_n), jnp.where(take_p, bh_p, bh_n),
            jnp.where(take_p, il_p, il_n), jnp.where(take_p, bl_p, bl_n))


def _gather_row(x_loc, owner_mask):
    """Fetch one global row from the sharded X by masked psum — the
    replicated-X read `g_x[i]` of the reference (svmTrain.cu:222) without
    replicating X."""
    contrib = jnp.sum(jnp.where(owner_mask[:, None], x_loc.astype(jnp.float32), 0.0),
                      axis=0)
    return lax.psum(contrib, DATA_AXIS)


def _gather_scalar(v_loc, owner_mask):
    return lax.psum(jnp.sum(jnp.where(owner_mask, v_loc, 0.0)), DATA_AXIS)


def _pair_update_local(state, y_loc, own_hi, own_lo, b_hi_pair, b_lo_pair,
                       k_hi, k_lo, eta, c, gate=None):
    """Shared distributed tail: replicated alpha-pair algebra + local
    scatter + local rank-2 f update. `c` is (c_pos, c_neg). `gate=False`
    forces an exact no-op (see solver/smo.py _apply_pair_update).
    Returns (alpha, f, f_err); the Kahan residual is carried shard-local
    exactly like f itself (config.compensated)."""
    from dpsvm_tpu.solver.smo import pair_alpha_update

    cp, cn = split_c(c)
    y_hi = _gather_scalar(y_loc, own_hi)
    y_lo = _gather_scalar(y_loc, own_lo)
    a_hi_old = _gather_scalar(state.alpha, own_hi)
    a_lo_old = _gather_scalar(state.alpha, own_lo)
    a_hi_new, a_lo_new = pair_alpha_update(
        a_hi_old, a_lo_old, y_hi, y_lo, b_hi_pair, b_lo_pair, eta,
        c_of(y_hi, cp, cn), c_of(y_lo, cp, cn), gate)
    # lo writes first, hi wins on i_hi == i_lo (matches seq.cpp:248-251).
    alpha = jnp.where(own_lo, a_lo_new, state.alpha)
    alpha = jnp.where(own_hi, a_hi_new, alpha)
    if state.f_err is None:
        # Association kept bit-identical to the pre-compensation engine
        # (mesh/single-chip trajectory parity is calibrated against it).
        f = state.f + (a_hi_new - a_hi_old) * y_hi * k_hi \
                    + (a_lo_new - a_lo_old) * y_lo * k_lo
        return alpha, f, None
    delta = (a_hi_new - a_hi_old) * y_hi * k_hi \
        + (a_lo_new - a_lo_old) * y_lo * k_lo
    f, err = kahan_add(state.f, state.f_err, delta)
    return alpha, f, err


def _iteration_wss2(x_loc, y_loc, x_sq_loc, k_diag_loc, valid_loc,
                    state: SMOState, kp: KernelParams, c: float, tau: float,
                    use_cache: bool) -> SMOState:
    """Distributed second-order (WSS2) iteration: i by max violation
    (first all_gather round), j by max second-order gain over the sharded
    candidates (second all_gather round). See solver/smo.py
    _smo_iteration_wss2 for the single-chip derivation."""
    n_loc = x_loc.shape[0]
    gids = _global_ids(n_loc)
    cp, cn = split_c(c)
    f_cur = eff_f(state)
    up = up_mask(state.alpha, y_loc, cp, cn) & valid_loc
    low = low_mask(state.alpha, y_loc, cp, cn) & valid_loc
    f_up = jnp.where(up, f_cur, jnp.inf)
    f_low = jnp.where(low, f_cur, -jnp.inf)
    l_hi = jnp.argmin(f_up).astype(jnp.int32)

    # Round 1: global i (min f over I_up) + global b_lo (convergence gap).
    cand_vals = jnp.stack([f_up[l_hi], jnp.max(f_low)])
    cand_idx = jnp.stack([gids[l_hi], jnp.int32(0)])
    g_vals = lax.all_gather(cand_vals, DATA_AXIS)
    g_idx = lax.all_gather(cand_idx, DATA_AXIS)
    b_hi = jnp.min(g_vals[:, 0])
    i_hi = jnp.min(jnp.where(g_vals[:, 0] == b_hi, g_idx[:, 0], _I32_MAX))
    b_lo = jnp.max(g_vals[:, 1])

    own_hi = gids == i_hi
    q_hi = _gather_row(x_loc, own_hi)
    q_hi_sq = _gather_scalar(x_sq_loc, own_hi)  # see _iteration: bit-parity
    stamp = 2 * state.it.astype(jnp.int32)
    if use_cache:
        d_hi, cache, hit_hi = lookup_one(
            state.cache, x_loc, i_hi, q_hi.astype(x_loc.dtype), stamp + 1)
    else:
        from dpsvm_tpu.ops.kernels import row_dots
        d_hi, cache, hit_hi = (row_dots(x_loc, q_hi.astype(x_loc.dtype)),
                               state.cache, jnp.bool_(False))
    k_hi = kernel_from_dots(d_hi, x_sq_loc, q_hi_sq, kp)

    # Round 2: global j by second-order gain over local I_low candidates.
    # K(hi,hi) is gathered from the precomputed diagonal (not recomputed
    # from q_hi) so the reduction is bit-identical to the single-chip
    # path's k_diag[i_hi] and trajectories stay aligned across backends.
    k_hh = _gather_scalar(k_diag_loc, own_hi)
    diff = f_cur - b_hi
    eta_j = jnp.maximum(k_hh + k_diag_loc - 2.0 * k_hi, tau)
    gain = jnp.where(low & (diff > 0), diff * diff / eta_j, -jnp.inf)
    l_lo = jnp.argmax(gain).astype(jnp.int32)
    g_gain = lax.all_gather(gain[l_lo], DATA_AXIS)
    g_jidx = lax.all_gather(gids[l_lo], DATA_AXIS)
    best = jnp.max(g_gain)
    any_elig = best > -jnp.inf
    i_lo = jnp.where(any_elig,
                     jnp.min(jnp.where(g_gain == best, g_jidx, _I32_MAX)),
                     i_hi).astype(jnp.int32)
    own_lo = gids == i_lo
    b_lo_pair = _gather_scalar(f_cur, own_lo)

    q_lo = _gather_row(x_loc, own_lo)
    q_lo_sq = _gather_scalar(x_sq_loc, own_lo)  # see _iteration: bit-parity
    if use_cache:
        d_lo, cache, hit_lo = lookup_one(
            cache, x_loc, i_lo, q_lo.astype(x_loc.dtype), stamp + 2)
    else:
        from dpsvm_tpu.ops.kernels import row_dots
        d_lo, hit_lo = row_dots(x_loc, q_lo.astype(x_loc.dtype)), jnp.bool_(False)
    k_lo = kernel_from_dots(d_lo, x_sq_loc, q_lo_sq, kp)

    # Same bit-identical sourcing for the final eta: diagonal entries from
    # k_diag, cross term from the fetched hi row (matches single-chip
    # k_hi[i_lo]).
    k_ll = _gather_scalar(k_diag_loc, own_lo)
    k_hl = _gather_scalar(k_hi, own_lo)
    eta = jnp.maximum(k_hh + k_ll - 2.0 * k_hl, tau)
    n_hits = hit_hi.astype(jnp.int32) + hit_lo.astype(jnp.int32)
    alpha, f, f_err = _pair_update_local(state, y_loc, own_hi, own_lo, b_hi,
                                         b_lo_pair, k_hi, k_lo, eta, c,
                                         gate=any_elig)
    return SMOState(alpha, f, b_hi, b_lo, state.it + 1, cache,
                    state.hits + n_hits, f_err)


def _iteration(x_loc, y_loc, x_sq_loc, k_diag_loc, valid_loc, state: SMOState,
               kp: KernelParams, c: float, tau: float, use_cache: bool,
               select_fn=_select_global) -> SMOState:
    """One distributed SMO iteration; runs identically on every device.
    `select_fn` swaps the C-SVC global MVP rule for the nu duals'
    per-class variant (see solver/smo.py)."""
    n_loc = x_loc.shape[0]
    i_hi, b_hi, i_lo, b_lo = select_fn(
        eff_f(state), state.alpha, y_loc, c, valid_loc)

    gids = _global_ids(n_loc)
    own_hi = gids == i_hi
    own_lo = gids == i_lo
    q_hi = _gather_row(x_loc, own_hi)
    q_lo = _gather_row(x_loc, own_lo)
    # Squared norms come from the precomputed x_sq (via one-hot psum), NOT
    # recomputed from the fetched row: a re-reduction can differ in the
    # last ulp from the setup-time value, which is enough to desync mesh
    # and single-chip trajectories (single-chip reads x_sq[i], smo.py).
    q_hi_sq = _gather_scalar(x_sq_loc, own_hi)
    q_lo_sq = _gather_scalar(x_sq_loc, own_lo)

    if use_cache:
        d_hi, d_lo, cache, n_hits = lookup_pair(
            state.cache, x_loc, i_hi, i_lo,
            q_hi.astype(x_loc.dtype), q_lo.astype(x_loc.dtype), state.it)
    else:
        from dpsvm_tpu.ops.kernels import row_dots
        d2 = row_dots(x_loc, jnp.stack([q_hi, q_lo]).astype(x_loc.dtype))
        d_hi, d_lo, cache, n_hits = d2[0], d2[1], state.cache, jnp.int32(0)

    k_hi = kernel_from_dots(d_hi, x_sq_loc, q_hi_sq, kp)
    k_lo = kernel_from_dots(d_lo, x_sq_loc, q_lo_sq, kp)

    # eta sourced from the fetched kernel rows (gathered at the owning
    # shard), bit-identical to the single-chip k_hi[i_hi]/k_lo[i_lo]/
    # k_hi[i_lo] reads so mesh and single-chip trajectories stay aligned.
    k_hh = _gather_scalar(k_hi, own_hi)
    k_ll = _gather_scalar(k_lo, own_lo)
    k_hl = _gather_scalar(k_hi, own_lo)
    eta = jnp.maximum(k_hh + k_ll - 2.0 * k_hl, tau)

    alpha, f, f_err = _pair_update_local(state, y_loc, own_hi, own_lo,
                                         b_hi, b_lo, k_hi, k_lo, eta, c)
    return SMOState(alpha, f, b_hi, b_lo, state.it + 1, cache,
                    state.hits + n_hits, f_err)


_ITERATION_FNS = {
    "mvp": _iteration,
    "second_order": _iteration_wss2,
    "nu": partial(_iteration, select_fn=_select_global_nu),
}


def _make_chunk_runner(mesh: Mesh, kp: KernelParams, c: float, eps: float,
                       tau: float, chunk: int, use_cache: bool,
                       selection: str = "mvp", compensated: bool = False):
    """Build the jitted shard_mapped chunk executor."""
    step = _ITERATION_FNS[selection]

    def chunk_body(x_loc, y_loc, x_sq_loc, k_diag_loc, valid_loc, state, max_iter):
        end = jnp.minimum(state.it + chunk, max_iter)

        def cond(st):
            return (st.it < end) & (st.b_lo > st.b_hi + 2.0 * eps)

        def body(st):
            return step(x_loc, y_loc, x_sq_loc, k_diag_loc, valid_loc, st,
                        kp, c, tau, use_cache)

        return lax.while_loop(cond, body, state)

    shard = P(DATA_AXIS)
    rep = P()
    state_specs = SMOState(
        alpha=shard, f=shard, b_hi=rep, b_lo=rep, it=rep,
        cache=CacheState(data=P(None, DATA_AXIS), keys=rep, ticks=rep),
        hits=rep,
        f_err=shard if compensated else None,
    )
    mapped = mesh_shard_map(
        chunk_body,
        mesh=mesh,
        in_specs=(shard, shard, shard, shard, shard, state_specs, rep),
        out_specs=state_specs,
        check=False,  # while_loop carries defeat the replication checker
    )
    return jax.jit(mapped)


def solve_mesh(
    x,
    y,
    config: SVMConfig,
    num_devices: Optional[int] = None,
    mesh: Optional[Mesh] = None,
    callback=None,
    checkpoint_path: Optional[str] = None,
    resume: bool = False,
    alpha_init=None,
    f_init=None,
    warm_start=None,
) -> SolveResult:
    """Train binary C-SVC sharded over the mesh's `data` axis.

    `alpha_init` / `f_init` override the standard start point exactly as in
    solver.smo.solve — the hook the SVR / one-class reductions use.
    `warm_start` is the high-level seed (solver/warmstart.py,
    ISSUE 18): repaired into this config's constraints, its gradient
    rebuilt through the ONE-PSUM mesh fold (seed rows gathered from the
    row-sharded X, local fold per shard — the warm_f_rebuild mesh
    budget), then delegated to alpha_init/f_init. An all-zero repaired
    seed routes bit-identically through the cold path.
    `callback` follows solve()'s contract, including abort-on-truthy-return
    at chunk boundaries and the donation caveat — the received state is
    donated to the next chunk, so copy what outlives the call (see
    solver/smo.py solve docstring).
    """
    if config.engine not in ("xla", "block"):
        raise ValueError(
            f"engine={config.engine!r} is implemented for the single-chip "
            "solver only; the mesh backend supports engine='xla' (per-pair) "
            "and engine='block' (distributed decomposition)")
    if config.active_set_size and config.engine != "block":
        raise ValueError(
            "active_set_size (shrinking) needs engine='block' "
            "(the per-pair engines have no cycle structure to restrict)")
    if config.kernel == "precomputed" and config.engine != "block":
        raise ValueError(
            "kernel='precomputed' on the mesh is implemented for "
            "engine='block' (Gram symmetry makes its fold a local column "
            "gather and the (q, q) block a q^2-sized psum — "
            "parallel/dist_block.py); the per-pair mesh engine would "
            "move a full (n,) Gram row per pair update — use "
            "engine='block' or backend='single'")
    if config.selection == "nu" and alpha_init is None:
        # See solver/smo.py: nu selection is degenerate without the nu
        # trainers' feasible warm start.
        raise ValueError(
            "selection='nu' is internal to the nu duals — call "
            "train_nusvc/train_nusvr (models/nusvm.py) instead")
    if config.ooc:
        # Out-of-core tile stream over the mesh (ISSUE 19): each device
        # owns a padded row shard's tiles — the host feeds every device
        # its shard's tile per double-buffered sharded put, folds are
        # local (zero collectives), and the round joins on ONE psum
        # inside selection. Bitwise equal to the single-chip stream
        # (solver/ooc.py solve_ooc_mesh; tests/test_ooc.py pins it at
        # 2 devices). Routed BEFORE the warm-start recursion below so
        # the ooc driver owns seed repair (its gradient rebuild is the
        # streamed fold, not the in-core one).
        from dpsvm_tpu.solver.ooc import solve_ooc_mesh

        return solve_ooc_mesh(x, y, config, num_devices=num_devices,
                              mesh=mesh, callback=callback,
                              checkpoint_path=checkpoint_path,
                              resume=resume, alpha_init=alpha_init,
                              f_init=f_init, warm_start=warm_start)
    if warm_start is not None:
        if alpha_init is not None or f_init is not None:
            raise ValueError(
                "pass either warm_start or alpha_init/f_init, not both")
        from dpsvm_tpu.solver.warmstart import prepare_warm_start

        n_dev = (int(mesh.size) if mesh is not None
                 else int(num_devices or len(jax.devices())))
        a0, f0, wstats = prepare_warm_start(x, y, config, warm_start,
                                            mesh_devices=n_dev)
        res = solve_mesh(x, y, config, num_devices=num_devices,
                         mesh=mesh, callback=callback,
                         checkpoint_path=checkpoint_path, resume=resume,
                         alpha_init=a0, f_init=f0)
        res.stats["warm_start"] = wstats
        return res
    if config.reconstruct_every:
        # f64 reconstruction legs around the mesh solve — same scheme as
        # the single-chip delegation (solver/reconstruct.py).
        from functools import partial as _partial

        from dpsvm_tpu.solver.reconstruct import solve_in_legs

        return solve_in_legs(
            _partial(solve_mesh, num_devices=num_devices, mesh=mesh),
            x, y, config, callback=callback,
            checkpoint_path=checkpoint_path, resume=resume,
            alpha_init=alpha_init, f_init=f_init)

    from dpsvm_tpu.solver.smo import (_precision_ctx, _retry_callback,
                                      _solve_with_degradation,
                                      run_with_fault_retry)

    def run(cfg, res):
        def attempt(cfg_k, res_k, k):
            return _solve_mesh_impl(x, y, cfg_k, num_devices, mesh,
                                    _retry_callback(callback, cfg_k,
                                                    checkpoint_path, k),
                                    checkpoint_path, res_k, alpha_init,
                                    f_init)

        # Single-controller retry only: on a multi-host pod a faulted
        # process cannot re-sync its peers' collectives mid-job, so
        # retries are forced OFF there automatically — recovery happens
        # by relaunching the whole job with --resume (checkpoints are
        # process-0-written and backend-portable), which the one-time
        # warning names.
        if jax.process_count() == 1:
            retry_cfg = cfg
        else:
            retry_cfg = cfg.replace(retry_faults=0)
            _warn_multihost_retry_dropped(cfg)
        with _precision_ctx(cfg):
            return run_with_fault_retry(retry_cfg, checkpoint_path, res,
                                        attempt)

    # Non-finite sentinel + safe-config demotion (ISSUE 13): the mesh
    # loop observes the same chunk-boundary extrema as the single-chip
    # driver, so it gets the same backstop.
    return _solve_with_degradation(config, checkpoint_path, resume, run)


def _solve_mesh_impl(x, y, config, num_devices, mesh, callback,
                     checkpoint_path, resume, alpha_init,
                     f_init) -> SolveResult:
    t_entry = time.perf_counter()  # phase clock: setup starts here
    use_block = config.engine == "block"
    x = np.asarray(x, np.float32)
    y_np = np.asarray(y, np.int32)
    n, d = x.shape
    gamma = config.resolve_gamma(d)
    kp = KernelParams(config.kernel, gamma, config.degree, config.coef0)
    dtype = jnp.bfloat16 if config.dtype == "bfloat16" else jnp.float32
    if config.dtype == "bfloat16":
        from dpsvm_tpu.ops.kernels import warn_if_bf16_degrades
        warn_if_bf16_degrades(x, config)
    # bf16 Gram path (config.bf16_gram): same gate + loud-refusal
    # contract as the single-chip path (solver/smo.py); the mesh
    # shards the bf16-stored X exactly as it would the f32 one.
    bf16_gram_stats = {}
    if config.bf16_gram:
        from dpsvm_tpu.ops.kernels import resolve_bf16_gram

        _bfg_on, _, _bfg_entry = resolve_bf16_gram(x, config, gamma)
        bf16_gram_stats = {"bf16_gram": _bfg_entry}
        if _bfg_on:
            dtype = jnp.bfloat16
        else:
            import warnings

            warnings.warn(_bfg_entry["note"], stacklevel=3)

    if mesh is None:
        mesh = make_data_mesh(num_devices)
    n_dev = mesh.devices.size

    # Fused fold+select (mesh counterpart of solver/block.py
    # run_chunk_block_fused): each shard's fold + candidate selection is
    # one Pallas pass; pays in the big-n_loc pod regime. The gate keys
    # on n_loc (each shard's round works its local rows) with the
    # d-aware measured crossover shared with the single-chip path
    # (solver/block.py fused_fold_pays — round-5 sweep covering the
    # n_loc band pods actually land in). Needs n_loc padded to 1024 and
    # q/2 <= n_loc/128.
    from dpsvm_tpu.solver.block import (autotune_gate_resolver,
                                        fused_fold_pays, pipeline_pays,
                                        ring_pays, shardlocal_pays)

    _platform = mesh.devices.flat[0].platform
    # Auto-gate resolution (ISSUE 14): None-valued knobs resolve
    # through the installed DeviceProfile for this device kind with
    # the hand-measured *_pays defaults as fallback; provenance of
    # every consulted gate lands in stats["autotune"] + the manifest
    # via _autotune_embed (the solver/smo.py contract).
    _auto_gate, _autotune_embed = autotune_gate_resolver(
        mesh.devices.flat[0])

    _n_pad_f = pad_rows(n, n_dev, multiple=1024)
    _n_loc_f = _n_pad_f // n_dev
    # Shard-parallel working sets (config.local_working_sets;
    # dist_block.py make_block_shardlocal_chunk_runner): P concurrent
    # shard-local subproblem chains per round, reconciled by one
    # touched-rows all_gather per sync — the engine that attacks the
    # replicated-chain Amdahl term directly. Takes precedence over the
    # pipelined/fused round variants (it removes the per-round
    # collectives those engines merely hide). The nu trainers fall back
    # to the plain runner silently (same contract as pair_batch) — their
    # per-class stopping pair does not reduce shard-locally.
    _lws = config.local_working_sets
    use_shardlocal = (use_block and config.selection != "nu"
                      and not config.active_set_size
                      and kp.kind != "precomputed"
                      and not config.budget_mode
                      and not config.pipeline_rounds
                      and (_lws >= 2 if _lws is not None
                           # Structural guard BEFORE the profile: a
                           # P=1 mesh is the pure-sync-overhead regime
                           # a kind-wide measured True (taken on P>=2)
                           # must not engage — same reason the probe
                           # itself skips below 2 devices.
                           else (n_dev > 1
                                 and _auto_gate(
                                     "local_working_sets",
                                     _platform == "tpu"
                                     and shardlocal_pays(_n_loc_f, d)))))
    # Pipelined mesh rounds (config.pipeline_rounds; dist_block.py
    # make_block_pipelined_chunk_runner): the per-round all_gather/psum
    # collectives are issued from the pre-fold carry and can hide behind
    # the replicated subproblem chain. Supersedes the fused fold+select
    # when both would apply (same precedence as the single-chip path).
    use_pipe = (use_block and not use_shardlocal
                and config.selection != "nu"
                and not config.active_set_size
                and kp.kind != "precomputed"
                and (config.pipeline_rounds
                     if config.pipeline_rounds is not None
                     # The MESH-specific knob ("pipeline_rounds_mesh",
                     # the pipeline_mesh probe): the mesh pipelined
                     # engine's overlap is structural (collective-async
                     # gather/psum racing the replicated chain) and
                     # must not be adjudicated by the single-chip
                     # probe's verdict — that engine merely reorders
                     # kernels and is expected to measure a LOSS.
                     else _auto_gate(
                         "pipeline_rounds_mesh",
                         _platform == "tpu"
                         and pipeline_pays(_n_loc_f, d))))
    # Ring-overlapped candidate exchange (config.ring_exchange;
    # ops/ring.py + dist_block.py _select_block_mesh_ring /
    # ring_fold_window): the per-round/per-window all_gather + psums
    # become remote-DMA ring hops, bit-identical trajectories. Composes
    # with the global, pipelined and shard-local runners; the active and
    # fused runners keep the all_gather path (config validates the
    # explicit-True conflicts), as do nu trainers (per-class quarters)
    # and one-device meshes (no hops).
    use_ring = (use_block and n_dev > 1
                and config.selection != "nu"
                and kp.kind != "precomputed"
                and not config.active_set_size
                and (config.ring_exchange
                     if config.ring_exchange is not None
                     else _auto_gate(
                         "ring_exchange",
                         _platform == "tpu"
                         and ring_pays(n_dev, _n_loc_f, d))))
    use_fused = (use_block and not use_pipe and not use_shardlocal
                 and not use_ring
                 and config.selection != "nu"
                 and not config.active_set_size
                 and kp.kind != "precomputed"
                 and min(config.working_set_size, _n_loc_f)
                 <= _n_loc_f // 64
                 and (config.fused_fold if config.fused_fold is not None
                      else (_platform == "tpu"
                            and fused_fold_pays(_n_loc_f, d))))
    if config.fused_round:
        # The one-HBM-pass round (ops/pallas_round.py) is single-chip:
        # its in-kernel gather/fold assume the full row set is locally
        # resident. Loud fallback, not a silent ignore (the PR 8
        # discipline) — the mesh keeps its own per-shard fused
        # fold+select machinery above.
        import warnings

        warnings.warn(
            "fused_round=True is a single-chip knob; solve_mesh keeps "
            "its per-shard fused fold+select path (config.fused_fold) "
            "— the forced one-pass round does not apply on the mesh",
            stacklevel=3)
    n_pad = _n_pad_f if use_fused else pad_rows(n, n_dev)
    if kp.kind == "precomputed":
        if n != d:
            raise ValueError(
                f"kernel='precomputed' needs the square (n, n) Gram "
                f"matrix as x; got {x.shape}")
        # Pad BOTH axes: rows shard over devices, and the runner's
        # symmetric column gathers index columns by the same padded
        # global ids (padded rows/columns are zero and masked out of
        # selection by `valid`).
        d = n_pad
    x_p = np.zeros((n_pad, d), np.float32)
    x_p[:n, :x.shape[1]] = x
    y_p = np.ones((n_pad,), np.float32)
    y_p[:n] = y_np
    valid = np.zeros((n_pad,), bool)
    valid[:n] = True

    shard = NamedSharding(mesh, P(DATA_AXIS))
    rep = NamedSharding(mesh, P())
    x_dev = jax.device_put(jnp.asarray(x_p, dtype), shard)
    y_dev = jax.device_put(jnp.asarray(y_p), shard)
    # x_sq computed on device from the STORED x (matters for bf16: squares
    # of the rounded values, exactly like the single-chip path) so mesh and
    # single-chip kernel values — and hence trajectories — are bit-equal.
    from dpsvm_tpu.ops.kernels import squared_norms
    if kp.kind == "precomputed":
        # x IS the Gram matrix: its diagonal is the kernel diagonal and
        # the squared-norm pass has no meaning (mirrors solver/smo.py).
        x_sq = jax.device_put(jnp.zeros((n_pad,), jnp.float32), shard)
        diag_p = np.zeros((n_pad,), np.float32)
        # Diagonal through the SAME storage rounding as x_dev (the
        # single-chip path reads jnp.diagonal of the stored-dtype array,
        # solver/smo.py): under dtype='bfloat16' eta must mix equal
        # precisions or mesh and single-chip trajectories diverge.
        import ml_dtypes
        diag_src = np.diagonal(x)
        if config.dtype == "bfloat16":
            diag_src = diag_src.astype(ml_dtypes.bfloat16)
        diag_p[:n] = diag_src.astype(np.float32)
        k_diag = jax.device_put(jnp.asarray(diag_p), shard)
    else:
        x_sq = jax.jit(squared_norms, out_shardings=shard)(x_dev)
        k_diag = jax.jit(kernel_diag, static_argnames="params",
                         out_shardings=shard)(x_sq, params=kp)
    valid_dev = jax.device_put(jnp.asarray(valid), shard)

    cache_lines = min(config.cache_lines, n_pad // n_dev)
    # The block engine has no LRU cache; don't allocate the (lines, n)
    # sharded cache array or report cache stats for it.
    use_cache = cache_lines > 0 and not use_block
    if use_block:
        cache_lines = 0
    state = SMOState(
        alpha=jax.device_put(jnp.zeros((n_pad,), jnp.float32), shard),
        f=jax.device_put(jnp.asarray(-y_p, jnp.float32), shard),
        b_hi=jax.device_put(jnp.float32(-jnp.inf), rep),
        b_lo=jax.device_put(jnp.float32(jnp.inf), rep),
        it=jax.device_put(jnp.int32(0), rep),
        cache=jax.tree.map(
            lambda a, s: jax.device_put(a, s),
            init_cache(max(cache_lines, 1), n_pad),
            CacheState(data=NamedSharding(mesh, P(None, DATA_AXIS)), keys=rep, ticks=rep)),
        hits=jax.device_put(jnp.int32(0), rep),
    )
    if alpha_init is not None:
        a_p = np.zeros((n_pad,), np.float32)
        a_p[:n] = np.asarray(alpha_init, np.float32)
        state = state._replace(alpha=jax.device_put(jnp.asarray(a_p), shard))
    if f_init is not None:
        f_p = np.asarray(-y_p, np.float32)
        f_p[:n] = np.asarray(f_init, np.float32)
        state = state._replace(f=jax.device_put(jnp.asarray(f_p), shard))
    from dpsvm_tpu.utils.checkpoint import PeriodicCheckpointer, resume_solver_state

    if resume:
        restored = resume_solver_state(checkpoint_path, config, n)
        if restored is not None:
            a0, f0, it0, bh0, bl0 = restored
            a_p = np.zeros((n_pad,), np.float32)
            a_p[:n] = a0
            f_p = np.asarray(-y_p, np.float32)
            f_p[:n] = f0
            state = state._replace(
                alpha=jax.device_put(jnp.asarray(a_p), shard),
                f=jax.device_put(jnp.asarray(f_p), shard),
                b_hi=jax.device_put(jnp.float32(bh0), rep),
                b_lo=jax.device_put(jnp.float32(bl0), rep),
                it=jax.device_put(jnp.int32(it0), rep))
    if config.compensated:
        state = state._replace(
            f_err=jax.device_put(jnp.zeros((n_pad,), jnp.float32), shard))
    max_iter = jnp.int32(config.max_iter)
    start_iter = int(state.it)
    ckpt = PeriodicCheckpointer(checkpoint_path, config, start_iter)
    # One dispatch to convergence when nothing observes chunk boundaries
    # (device->host transfers are the expensive primitive; see solver/smo.py
    # _UNOBSERVED_CHUNK).
    from dpsvm_tpu.solver.smo import (_BUDGET_EPS, _UNOBSERVED_CHUNK,
                                      _pack_obs, _unpack_obs)

    # budget_mode: same contract as the single-chip solver — the chunk
    # runners compile the stopping test with _BUDGET_EPS so the loop runs
    # to the exact max_iter pair budget; `converged` is re-derived from
    # the final state at the real epsilon below.
    eps_run = _BUDGET_EPS if config.budget_mode else float(config.epsilon)

    observe = (callback is not None or config.verbose
               or config.check_numerics or ckpt.active)
    chunk_len = int(config.chunk_iters) if observe else _UNOBSERVED_CHUNK
    if use_block:
        from dpsvm_tpu.parallel.dist_block import make_block_chunk_runner
        from dpsvm_tpu.solver.block import BlockState

        # Block height clamped so each shard can produce q/2 candidates
        # (q/4 per class quarter under the nu rule).
        n_loc = n_pad // n_dev
        gran = 4 if config.selection == "nu" else 2
        q = max(gran, min(config.working_set_size, gran * n_loc))
        q -= q % gran
        inner = config.inner_iters or 2 * q
        rounds_per_chunk = (max(1, chunk_len // inner)
                            if observe else _UNOBSERVED_CHUNK)
        inner_impl = "pallas" if _platform == "tpu" else "xla"

        def _plain_runner(rpc):
            # Shared by the default dispatch and the shard-local
            # engine's endgame demotion (which swaps runners mid-solve).
            # The ring exchange rides along (bit-identical either way,
            # so the demotion contract is unchanged).
            return make_block_chunk_runner(
                mesh, kp, config.c_bounds(), eps_run,
                float(config.tau), q, inner, rpc, inner_impl,
                interpret=_platform != "tpu",
                selection=config.selection,
                compensated=config.compensated,
                pair_batch=int(config.pair_batch),
                donate_state=True, ring_exchange=use_ring)

        if config.active_set_size:
            from dpsvm_tpu.parallel.dist_block import (
                make_block_active_chunk_runner)

            # Active-set size clamped like q: [q, gran*n_loc] so each
            # shard can supply m/gran candidates per selection side, on
            # the class granularity (see make_block_active_chunk_runner).
            m_act = max(q, min(config.active_set_size, gran * n_loc))
            m_act -= m_act % gran
            run_chunk = make_block_active_chunk_runner(
                mesh, kp, config.c_bounds(), eps_run,
                float(config.tau), q, inner, rounds_per_chunk,
                m_act, int(config.reconcile_rounds), inner_impl,
                selection=config.selection,
                compensated=config.compensated,
                pair_batch=int(config.pair_batch),
                donate_state=True)
        elif use_shardlocal:
            from dpsvm_tpu.parallel.dist_block import (
                make_block_shardlocal_chunk_runner)

            r_sync = int(config.sync_rounds)
            # The host-side ENDGAME DEMOTION must observe the gap at
            # chunk boundaries, so shard-local chunks are always bounded
            # to a few sync windows — never _UNOBSERVED_CHUNK (after
            # demotion the exact tail runner gets the usual cadence).
            # `rounds` here count LOCAL rounds; the while cond steps
            # whole windows, so the bound is a multiple of sync_rounds.
            win = (max(1, max(1, chunk_len // inner) // r_sync)
                   if observe else _SHARDLOCAL_WINDOWS_PER_CHUNK)
            run_chunk = make_block_shardlocal_chunk_runner(
                mesh, kp, config.c_bounds(), eps_run,
                float(config.tau), q, inner, win * r_sync, r_sync,
                inner_impl, interpret=_platform != "tpu",
                selection=config.selection,
                compensated=config.compensated,
                pair_batch=int(config.pair_batch),
                donate_state=True, ring_exchange=use_ring)
        elif use_pipe:
            from dpsvm_tpu.parallel.dist_block import (
                make_block_pipelined_chunk_runner)

            run_chunk = make_block_pipelined_chunk_runner(
                mesh, kp, config.c_bounds(), eps_run,
                float(config.tau), q, inner, rounds_per_chunk, inner_impl,
                interpret=_platform != "tpu",
                selection=config.selection,
                compensated=config.compensated,
                pair_batch=int(config.pair_batch),
                donate_state=True, ring_exchange=use_ring)
        elif use_fused:
            from dpsvm_tpu.parallel.dist_block import (
                make_block_fused_chunk_runner)

            run_chunk = make_block_fused_chunk_runner(
                mesh, kp, config.c_bounds(), eps_run,
                float(config.tau), q, inner, rounds_per_chunk, inner_impl,
                interpret=_platform != "tpu",
                selection=config.selection,
                compensated=config.compensated,
                pair_batch=int(config.pair_batch),
                donate_state=True)
        else:
            run_chunk = _plain_runner(rounds_per_chunk)
        state = BlockState(alpha=state.alpha, f=state.f, b_hi=state.b_hi,
                           b_lo=state.b_lo, pairs=state.it,
                           rounds=jax.device_put(jnp.int32(0), rep),
                           f_err=state.f_err)
    else:
        run_chunk = _make_chunk_runner(mesh, kp, config.c_bounds(),
                                       eps_run,
                                       float(config.tau), chunk_len,
                                       use_cache, config.selection,
                                       compensated=config.compensated)
    if callback is not None and hasattr(callback, "on_start"):
        callback.on_start(start_iter)

    # Observability (dpsvm_tpu/obs; NULL_OBS when disabled) + the honest
    # phase clock — same contract as solver/smo.py: obs never joins the
    # `observe` predicate (chunk cadence is unchanged), phase boundaries
    # sync ONCE, at chunk boundaries only (the setup sync below is the
    # first boundary; without it sharded staging rides into chunk 1).
    from dpsvm_tpu.obs import run_obs

    obs = run_obs("solve_mesh", config,
                  meta={"n": n, "d": d, "n_pad": n_pad,
                        "n_devices": n_dev,
                        "engine": config.engine,
                        "kernel": config.kernel,
                        "selection": config.selection,
                        "shardlocal": bool(use_shardlocal),
                        "pipelined": bool(use_block and use_pipe),
                        "fused_fold": bool(use_block and use_fused),
                        "ring_exchange": bool(use_ring),
                        "observed_chunks": observe,
                        **_autotune_embed()})
    from dpsvm_tpu.solver.smo import drain_pending_obs_events
    drain_pending_obs_events(obs)
    jax.block_until_ready((x_dev, y_dev, x_sq, k_diag, valid_dev, state))
    phase_seconds = {"setup": time.perf_counter() - t_entry,
                     "solve": 0.0, "observe": 0.0, "finalize": 0.0}

    # Device time only, clock stopped during host observation — see the
    # matching loop in solver/smo.py for the rationale.
    train_seconds = 0.0
    # Shard-local endgame demotion state (docs/ARCHITECTURE.md): the
    # concurrent shard-local chains are a BULK-phase accelerator; once
    # the global gap stops halving across a chunk of sync windows (the
    # remaining violators need cross-shard pairs no local chain can
    # form) or drops within 10x epsilon of done, the host swaps in the
    # exact global-working-set runner for the tail, so final
    # convergence and parity artifacts are identical to the plain
    # engine's.
    shardlocal_live = use_shardlocal
    shardlocal_demoted = False
    # Stall reference for the demotion test: (gap, rounds) at the last
    # halving. Measured in LOCAL ROUNDS, not chunks, so the test is
    # independent of the observation cadence (a verbose/callback run
    # shrinks chunks to ~1 sync window; requiring a halving per CHUNK
    # there would demote almost immediately and silently change engine
    # behavior between observed and unobserved runs of one config).
    gap_ref = None
    stall_rounds = (_SHARDLOCAL_WINDOWS_PER_CHUNK
                    * int(config.sync_rounds))
    dispatches = 0
    while True:
        with obs.span("mesh/chunk"):
            t0 = time.perf_counter()
            dispatches += 1
            faults.device_fault("dispatch", f"mesh chunk {dispatches}")
            state = run_chunk(x_dev, y_dev, x_sq, k_diag, valid_dev,
                              state, max_iter)
            jax.block_until_ready(state)
        chunk_dt = time.perf_counter() - t0
        train_seconds += chunk_dt
        t_obs0 = time.perf_counter()
        # Block-engine observability lags by <= one round here — see the
        # matching note in solver/smo.py (control flow is unaffected;
        # budget exits are refreshed exactly below).
        it, b_hi, b_lo = _unpack_obs(_pack_obs(
            state.pairs if use_block else state.it, state.b_hi, state.b_lo))
        # Non-finite sentinel (the solver/smo.py contract): a NaN gap
        # would read "converged" below and return a silently corrupt
        # model — raise for the demotion wrapper instead.
        b_hi, b_lo = faults.poison_obs(b_hi, b_lo)
        check_obs_finite(b_hi, b_lo, it, f"mesh p={n_dev}")
        obs.chunk(pairs=it, b_hi=b_hi, b_lo=b_lo,
                  device_seconds=chunk_dt, dispatch=dispatches,
                  shardlocal=bool(shardlocal_live))
        converged = not (b_lo > b_hi + 2.0 * eps_run)
        abort = bool(callback is not None
                     and callback(it, b_hi, b_lo, state))
        if config.check_numerics:
            assert_finite_state(state, it, f"mesh p={n_dev}")
        if ckpt.due(it) or (abort and ckpt.active):
            # The gate runs BEFORE the np.asarray materialization (hot
            # paths must not pull device arrays when nothing will be
            # written); abort exits force the save — the state being
            # stopped at must not exist only in memory.
            ckpt.save(it, np.asarray(state.alpha)[:n],
                      np.asarray(eff_f(state))[:n], b_hi, b_lo, force=True)
        if config.verbose:
            print(f"[smo-mesh p={n_dev}] iter={it} gap={b_lo - b_hi:.6f}")
        if shardlocal_live and not converged and it < config.max_iter:
            gap = float(b_lo) - float(b_hi)
            rounds_now = int(state.rounds)
            if gap_ref is None or gap <= 0.5 * gap_ref[0]:
                gap_ref = (gap, rounds_now)  # halved: advance the ref
            stalled = rounds_now - gap_ref[1] >= stall_rounds
            if gap <= 10.0 * float(config.epsilon) or stalled:
                run_chunk = _plain_runner(rounds_per_chunk)
                shardlocal_live = False
                shardlocal_demoted = True
                obs.event("shardlocal_demotion", pairs=it,
                          gap=float(gap), stalled=bool(stalled),
                          rounds=int(rounds_now))
                if config.verbose:
                    why = (f"gap not halved in {stall_rounds} local "
                           "rounds" if stalled
                           else f"gap within 10x epsilon ({gap:.6f})")
                    print(f"[smo-mesh p={n_dev}] shard-local endgame "
                          f"demotion at iter={it}: {why} -> exact "
                          "global-working-set runner")
        phase_seconds["observe"] += time.perf_counter() - t_obs0
        if converged or it >= config.max_iter:
            break
        if abort:
            # See solver/smo.py: clean callback stop, checked after the
            # convergence test so it cannot mask a converged chunk.
            break

    t_fin0 = time.perf_counter()
    alpha = np.asarray(state.alpha)[:n]
    f_final = np.asarray(eff_f(state))[:n]
    if (use_block or config.budget_mode) and not converged:
        from dpsvm_tpu.ops.select import refresh_extrema_host

        b_hi, b_lo, converged = refresh_extrema_host(
            f_final, alpha, y_np, config.c_bounds(),
            config.epsilon, rule=config.selection)
    lookups = 2 * (it - start_iter) if use_cache else 0
    phase_seconds["solve"] = train_seconds
    phase_seconds["finalize"] = time.perf_counter() - t_fin0
    phase_seconds = {k: round(v, 6) for k, v in phase_seconds.items()}
    stats = {
        "num_devices": n_dev,
        "rows_padded": n_pad - n,
        "cache_hits": int(state.hits),
        "cache_lookups": lookups,
        "cache_hit_rate": (int(state.hits) / lookups) if lookups else 0.0,
        "f": f_final,
        # Honest per-phase wall clock (one block_until_ready per
        # boundary, chunk boundaries only — see the phase-clock note
        # above and solver/smo.py's matching contract).
        "phase_seconds": phase_seconds,
        **({"outer_rounds": int(state.rounds)} if use_block else {}),
        **({"shardlocal_demoted": shardlocal_demoted}
           if use_shardlocal else {}),
        **({"ring_exchange": True} if use_ring else {}),
        **bf16_gram_stats,
        # Auto-gate provenance (ISSUE 14; the solver/smo.py contract).
        **_autotune_embed(),
    }
    if obs.live:
        stats["obs_run_id"] = obs.run_id
        stats["obs_runlog"] = obs.path
    obs.finish(iterations=it, converged=bool(converged),
               train_seconds=round(train_seconds, 6),
               dispatches=dispatches,
               b_hi=float(b_hi), b_lo=float(b_lo),
               shardlocal_demoted=bool(shardlocal_demoted),
               phase_seconds=phase_seconds)
    return SolveResult(
        alpha=alpha,
        b=float((b_lo + b_hi) / 2.0),
        b_hi=b_hi,
        b_lo=b_lo,
        iterations=it,
        converged=converged,
        train_seconds=train_seconds,
        dispatches=dispatches,
        stats=stats,
    )
