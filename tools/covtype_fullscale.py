"""Full-n covtype quality trajectory (VERDICT r3 item 3).

Runs the reference's covtype stress config (c=2048, gamma=0.03125, eps
0.001 — reference Makefile:77) at the FULL n=500k with a real
optimization budget (default 300M pairs vs the reference's 3M-pair cap),
recording a train-accuracy + gap trajectory, and appends it to
BENCH_COVTYPE.md. This turns round 3's "0.97 achievable (shown at
n=20k)" extrapolation into a measured full-scale curve.

Operating point: block engine (fused fold+select on TPU), fp32 X,
Kahan-compensated gradient carry (the carried f then stays accurate
enough to read train accuracy directly off it: dec_i = f_i + y_i - b,
zero extra compute), default matmul precision (r3 measured 0.97+
accuracy at this precision on the n=20k anchor; the 1e-3-gap
certification story lives in PARITY.md, not here). Dispatches are kept
to a few seconds via chunked observation; solver-level checkpointing +
automatic fault retry ride along, so a tunnel fault costs at most one
chunk.

Run: `python tools/covtype_fullscale.py [--pairs 300000000]`
"""

from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tools.parity_common import replace_section

SECTION = "## full-n quality trajectory (n=500k, measured)"


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--pairs", type=int, default=300_000_000)
    ap.add_argument("--q", type=int, default=512)
    ap.add_argument("--inner", type=int, default=1024)
    ap.add_argument("--chunk", type=int, default=8_000_000)
    ap.add_argument("--acc-every", type=int, default=20_000_000,
                    help="pairs between accuracy reads (each pulls f, "
                         "~2 MB device->host)")
    args = ap.parse_args()

    from dpsvm_tpu.config import SVMConfig
    from dpsvm_tpu.solver.smo import solve
    from tools.bench_covtype import make_data

    x, y = make_data()
    n = len(y)
    cfg = SVMConfig(c=2048.0, gamma=0.03125, epsilon=1e-3,
                    max_iter=args.pairs, engine="block",
                    working_set_size=args.q, inner_iters=args.inner,
                    compensated=True, matmul_precision="default",
                    dtype="float32", chunk_iters=args.chunk,
                    checkpoint_every=args.chunk, pair_batch=2)
    ck = os.path.join(REPO, "artifacts", "covtype_fullscale_ck.npz")
    # Trajectory + device-seconds accumulate ACROSS invocations (the
    # solve resumes from its checkpoint, so res.iterations is cumulative
    # while train_seconds covers only this process).
    sidecar = os.path.join(REPO, "artifacts", "covtype_fullscale_traj.json")
    hist = {"rows": [], "device_s": 0.0, "pairs_done": 0}
    if os.path.exists(sidecar):
        import json
        with open(sidecar) as fh:
            hist.update(json.load(fh))

    traj = []  # (pairs, gap, acc or None)
    t_state = {"acc_pairs": -args.acc_every}

    def acc_from_f(f, bh, bl):
        b = (bh + bl) / 2.0
        dec = np.asarray(f, np.float64) + y - b
        return float(np.mean(np.where(dec >= 0, 1, -1) == y))

    def cb(it, bh, bl, st):
        from dpsvm_tpu.solver.smo import eff_f

        gap = bl - bh
        acc = None
        if it - t_state["acc_pairs"] >= args.acc_every:
            t_state["acc_pairs"] = it
            acc = acc_from_f(np.asarray(eff_f(st))[:n], bh, bl)
        traj.append((int(it), float(gap), acc))
        print(f"  pairs={it:>11,} gap={gap:9.5f}"
              + (f" train_acc={acc:.4f}" if acc is not None else ""),
              flush=True)

    t0 = time.perf_counter()
    res = solve(x, y, cfg, callback=cb, checkpoint_path=ck, resume=True)
    wall = time.perf_counter() - t0
    final_acc = acc_from_f(res.stats["f"], res.b_hi, res.b_lo)
    this_pairs = res.iterations - hist["pairs_done"]
    pps = this_pairs / max(res.train_seconds, 1e-9)
    print(f"done: pairs={res.iterations:,} (+{this_pairs:,}) "
          f"device_s={res.train_seconds:.1f} wall_s={wall:.1f} "
          f"pairs/s={pps:,.0f} gap={res.b_lo - res.b_hi:.5f} "
          f"train_acc={final_acc:.4f}", flush=True)

    # Thin the trajectory for the table: keep accuracy rows + endpoints.
    rows = [t for t in traj if t[2] is not None]
    if traj and (not rows or rows[-1][0] != traj[-1][0]):
        # The endpoint's accuracy IS known — final_acc comes from the
        # returned state at exactly this pair count — so the table's
        # last row must not contradict the headline with an empty cell.
        rows.append((traj[-1][0], traj[-1][1],
                     final_acc if traj[-1][0] == res.iterations else None))
    import json
    hist["rows"] = [r for r in hist["rows"] if r[0] < (rows[0][0] if rows
                                                       else 10 ** 18)]
    hist["rows"] += [list(r) for r in rows]
    hist["device_s"] += res.train_seconds
    hist["pairs_done"] = int(res.iterations)
    with open(sidecar, "w") as fh:
        json.dump(hist, fh)
    rows = [tuple(r) for r in hist["rows"]]
    device_s = hist["device_s"]
    pps = res.iterations / max(device_s, 1e-9)

    lines = [
        SECTION, "",
        f"The reference caps its covtype run at 3M pair updates "
        f"(Makefile:77) and reports no accuracy; this run gives the SAME "
        f"config (c=2048, gamma=0.03125, n=500k, d=54, fp32) a real "
        f"optimization budget on one v5e chip — block engine "
        f"(fused fold+select, pair_batch=2), q={args.q}, "
        f"inner={args.inner}, "
        f"Kahan-compensated gradient carry (train accuracy is read "
        f"directly off the carried gradient: dec = f + y - b). "
        f"**{res.iterations:,} pair updates in "
        f"{device_s:.1f} device-seconds "
        f"({pps:,.0f} pairs/s), final train accuracy "
        f"{final_acc:.4f}**, stopping-rule gap "
        f"{res.b_lo - res.b_hi:.4f}.", "",
        "| pair updates | gap (b_lo - b_hi) | train accuracy |",
        "|---|---|---|",
    ]
    for it, gap, acc in rows:
        lines.append(f"| {it:,} | {gap:.5f} | "
                     f"{'' if acc is None else f'{acc:.4f}'} |")
    lines += [
        "",
        f"(final row re-read from the returned state: accuracy "
        f"{final_acc:.4f} at {res.iterations:,} pairs; device time "
        f"excludes the per-chunk host observation, solver/smo.py timing "
        f"discipline)", "",
        "**The accuracy ceiling is the generator's Bayes rate, not the "
        "solver.** The benchmark labels are y = sign(x_0 + 0.2 z) with "
        "x_0 ~ N(0, 0.3^2), z ~ N(0, 1), whose Bayes-optimal accuracy "
        "is 1 - arctan(0.2/0.3)/pi = 0.8128 (verified numerically on "
        "2e7 draws: 0.8130). The measured curve plateaus at ~0.807-0.81 "
        "= 99.3% of that ceiling while the KKT gap keeps falling - the "
        "optimization is still progressing; the ACCURACY is "
        "information-limited. The n=20k anchor's 0.973 train accuracy "
        "(BENCH_COVTYPE sweep section) is what changes: at 25x lower "
        "point density the fixed-gamma kernel can memorize label noise "
        "(C=2048 permits it); at n=500k neighboring points carry "
        "conflicting labels inside one kernel bandwidth and no solver "
        "can fit them. Train accuracy >= 0.9 at n=500k is therefore "
        "IMPOSSIBLE for this generator - the honest full-scale quality "
        "statement is accuracy/Bayes = 0.993 with the gap trajectory "
        "still descending.", ""]
    path = os.path.join(REPO, "BENCH_COVTYPE.md")
    replace_section(path, SECTION, lines)
    print(f"wrote {path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
