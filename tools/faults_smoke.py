"""Fault-tolerance smoke (ISSUE 13) — `make faults_smoke`, wired into
tier1.yml.

Five checks, each proving an acceptance behavior with a REAL injected
fault (dpsvm_tpu/testing/faults.py), end to end:

1. **Harness self-test** — spec parsing, deterministic arrival firing,
   seeded byte corruption reproducibility, env-var activation.
2. **ooc kill -9 / --resume** — a subprocess training out-of-core with
   periodic checkpoints is SIGKILLed mid-solve (nothing can be
   flushed); a relaunch with resume lands BITWISE on the uninterrupted
   run's alpha/f/extrema. This is the acceptance criterion verbatim,
   as a process-level kill rather than an in-process abort.
3. **mesh-ooc kill -9 / --resume** (ISSUE 19) — the same kill, against
   the MESH out-of-core stream at 2 virtual devices; the v2
   checkpoint's gathered carry must put the resumed sharded stream
   BITWISE on the uninterrupted trajectory.
4. **Watchdog trip** — a stalled dispatch (serve_stall seam) must be
   bounded by ServeConfig.dispatch_timeout_ms, fail with an explicit
   'failed' verdict + counters, and leave the engine serving the next
   batch.
5. **Lock stall** (ISSUE 20) — DPSVM_FAULTS="lock_stall@N" holds
   ModelRegistry._lock inside get()'s critical section while other
   threads contend for it; with the threadlint ORDER contract acyclic
   the fabric is delayed, never wedged: bounded wall clock, zero
   watchdog trips, every verdict 'ok'.

Runs on the CPU harness (JAX_PLATFORMS=cpu), no artifacts written;
exit 0 = all behaviors held.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def check_harness() -> None:
    from dpsvm_tpu.testing import faults

    plan = faults.FaultPlan.parse("dispatch@3,ooc_tile_put@2x2")
    fires = [plan.arrive("dispatch") for _ in range(5)]
    assert fires == [False, False, True, False, False], fires
    fires = [plan.arrive("ooc_tile_put") for _ in range(4)]
    assert fires == [False, True, True, False], fires
    assert plan.fired == {"dispatch": 1, "ooc_tile_put": 2}, plan.fired
    try:
        faults.FaultPlan.parse("not_a_seam")
        raise AssertionError("typo'd seam accepted")
    except ValueError:
        pass
    # Disarmed: no plan, every arrival is a no-op False.
    assert faults.active_plan() is None
    assert not faults.arrive("dispatch")
    # Seeded corruption is reproducible and genuinely corrupting.
    import tempfile

    tmp = tempfile.mkdtemp(prefix="dpsvm_faults_smoke_")
    src = os.path.join(tmp, "m.npz")
    np.savez_compressed(src, a=np.arange(4096, dtype=np.float32))
    c1 = faults.corrupt_npz_file(src, os.path.join(tmp, "c1.npz"), seed=3)
    c2 = faults.corrupt_npz_file(src, os.path.join(tmp, "c2.npz"), seed=3)
    with open(c1, "rb") as f1, open(c2, "rb") as f2:
        assert f1.read() == f2.read(), "corruption not deterministic"
    try:
        np.load(c1)["a"].sum()
        raise AssertionError("corrupted npz loaded cleanly")
    except AssertionError:
        raise
    except Exception:
        pass
    print("[faults_smoke] harness self-test OK")


_CHILD = r"""
import sys, time
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
sys.path.insert(0, {repo!r})
from dpsvm_tpu.config import SVMConfig
from dpsvm_tpu.data.synth import make_blobs_binary
from dpsvm_tpu.solver.smo import solve

x, y = make_blobs_binary(n=1024, d=24, seed=11, sep=1.0)
cfg = SVMConfig(c=2.0, epsilon=1e-3, engine="block", working_set_size=64,
                max_iter=50_000, ooc=True, ooc_tile_rows=256,
                compensated=True, checkpoint_every=128, retry_faults=0)
slow = "--slow" in sys.argv
def cb(it, bh, bl, st):
    if slow:
        time.sleep(0.02)  # widen the kill window
res = solve(x, y, cfg, callback=cb, checkpoint_path={ck!r}, resume=True)
np.savez({out!r}, alpha=res.alpha, f=res.stats["f"],
         b_hi=np.float64(res.b_hi), b_lo=np.float64(res.b_lo),
         iterations=res.iterations, converged=res.converged)
print("DONE", res.iterations, flush=True)
"""


_CHILD_MESH = r"""
import sys, time
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
sys.path.insert(0, {repo!r})
from dpsvm_tpu.config import SVMConfig
from dpsvm_tpu.data.synth import make_blobs_binary
from dpsvm_tpu.parallel.dist_smo import solve_mesh

x, y = make_blobs_binary(n=1024, d=24, seed=11, sep=1.0)
cfg = SVMConfig(c=2.0, epsilon=1e-3, engine="block", working_set_size=64,
                max_iter=50_000, ooc=True, ooc_tile_rows=256,
                checkpoint_every=128, retry_faults=0)
slow = "--slow" in sys.argv
def cb(it, bh, bl, st):
    if slow:
        time.sleep(0.02)  # widen the kill window
res = solve_mesh(x, y, cfg, num_devices=2, callback=cb,
                 checkpoint_path={ck!r}, resume=True)
np.savez({out!r}, alpha=res.alpha, f=res.stats["f"],
         b_hi=np.float64(res.b_hi), b_lo=np.float64(res.b_lo),
         iterations=res.iterations, converged=res.converged)
print("DONE", res.iterations, flush=True)
"""


def check_ooc_mesh_kill_resume() -> None:
    """kill -9 mid-MESH-ooc-solve (2 virtual devices), then --resume:
    bitwise-equal final state (ISSUE 19 — the v2 checkpoint carries
    the full gathered carry, so the sharded stream resumes on the
    uninterrupted trajectory exactly like the single-chip one)."""
    import tempfile

    tmp = tempfile.mkdtemp(prefix="dpsvm_faults_smoke_")
    ck = os.path.join(tmp, "mesh.ck.npz")
    out = os.path.join(tmp, "mesh.result.npz")
    ref = os.path.join(tmp, "mesh.ref.npz")
    code = _CHILD_MESH.format(repo=REPO, ck=ck, out=out)
    from dpsvm_tpu.utils.hostenv import cleaned_cpu_env

    env = cleaned_cpu_env(2)

    proc = subprocess.Popen([sys.executable, "-c", code, "--slow"],
                            env=env, stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE)
    deadline = time.time() + 180
    try:
        while time.time() < deadline and not os.path.exists(ck):
            if proc.poll() is not None:
                raise AssertionError(
                    "mesh child finished before a checkpoint appeared: "
                    + proc.stderr.read().decode()[-500:])
            time.sleep(0.05)
        assert os.path.exists(ck), "no mesh ooc checkpoint within 180s"
        time.sleep(0.3)  # advance past the first checkpoint
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=30)
    finally:
        if proc.poll() is None:
            proc.kill()
    assert not os.path.exists(out), "mesh child should have died mid-run"
    print("[faults_smoke] SIGKILLed mesh-ooc child mid-solve "
          f"(checkpoint at {ck})")

    r = subprocess.run([sys.executable, "-c", code], env=env,
                       capture_output=True, timeout=600)
    assert r.returncode == 0, r.stderr.decode()[-800:]
    z = np.load(out)
    assert bool(z["converged"])

    # Uninterrupted reference in a FRESH 2-device child (this parent
    # process is a 1-device platform).
    code_ref = _CHILD_MESH.format(repo=REPO, ck=os.path.join(
        tmp, "mesh.ref.ck.npz"), out=ref)
    r = subprocess.run([sys.executable, "-c", code_ref], env=env,
                       capture_output=True, timeout=600)
    assert r.returncode == 0, r.stderr.decode()[-800:]
    full = np.load(ref)
    assert int(z["iterations"]) == int(full["iterations"])
    np.testing.assert_array_equal(z["alpha"], full["alpha"])
    np.testing.assert_array_equal(z["f"], full["f"])
    assert float(z["b_hi"]) == float(full["b_hi"])
    assert float(z["b_lo"]) == float(full["b_lo"])
    print("[faults_smoke] mesh-ooc kill -9 -> resume BITWISE-equal "
          f"({int(full['iterations'])} pairs, 2 devices) OK")


def check_ooc_kill_resume() -> None:
    """kill -9 mid-ooc-solve, then --resume: bitwise-equal final state
    (the ISSUE 13 acceptance criterion)."""
    import tempfile

    from dpsvm_tpu.config import SVMConfig
    from dpsvm_tpu.data.synth import make_blobs_binary
    from dpsvm_tpu.solver.smo import solve
    from dpsvm_tpu.utils.hostenv import cleaned_cpu_env

    tmp = tempfile.mkdtemp(prefix="dpsvm_faults_smoke_")
    ck = os.path.join(tmp, "ooc.ck.npz")
    out = os.path.join(tmp, "ooc.result.npz")
    code = _CHILD.format(repo=REPO, ck=ck, out=out)
    env = cleaned_cpu_env(1)

    proc = subprocess.Popen([sys.executable, "-c", code, "--slow"],
                            env=env, stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE)
    deadline = time.time() + 180
    try:
        while time.time() < deadline and not os.path.exists(ck):
            if proc.poll() is not None:
                raise AssertionError(
                    "child finished before a checkpoint appeared: "
                    + proc.stderr.read().decode()[-500:])
            time.sleep(0.05)
        assert os.path.exists(ck), "no ooc checkpoint within 180s"
        time.sleep(0.3)  # advance past the first checkpoint
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=30)
    finally:
        if proc.poll() is None:
            proc.kill()
    assert not os.path.exists(out), "child should have died mid-run"
    print("[faults_smoke] SIGKILLed ooc child mid-solve "
          f"(checkpoint at {ck})")

    r = subprocess.run([sys.executable, "-c", code], env=env,
                       capture_output=True, timeout=600)
    assert r.returncode == 0, r.stderr.decode()[-800:]
    z = np.load(out)
    assert bool(z["converged"])

    x, y = make_blobs_binary(n=1024, d=24, seed=11, sep=1.0)
    full = solve(x, y, SVMConfig(c=2.0, epsilon=1e-3, engine="block",
                                 working_set_size=64, max_iter=50_000,
                                 ooc=True, ooc_tile_rows=256,
                                 compensated=True))
    assert int(z["iterations"]) == full.iterations
    np.testing.assert_array_equal(z["alpha"], full.alpha)
    np.testing.assert_array_equal(z["f"], full.stats["f"])
    assert float(z["b_hi"]) == full.b_hi
    assert float(z["b_lo"]) == full.b_lo
    print("[faults_smoke] ooc kill -9 -> resume BITWISE-equal "
          f"({full.iterations} pairs) OK")


def check_watchdog() -> None:
    from dpsvm_tpu.config import ServeConfig, SVMConfig
    from dpsvm_tpu.models.multiclass import train_multiclass
    from dpsvm_tpu.serving import ServingEngine
    from dpsvm_tpu.testing import faults

    rng = np.random.default_rng(7)
    x = np.concatenate([
        rng.normal(size=(60, 4)).astype(np.float32) + off
        for off in (0.0, 2.5)])
    y = np.repeat([0, 1], 60)
    model, _ = train_multiclass(
        x, y, SVMConfig(c=2.0, gamma=0.5, epsilon=1e-3), strategy="ovr")

    faults.STALL_SECONDS = 3.0
    eng = ServingEngine(ServeConfig(buckets=(16, 64),
                                    dispatch_timeout_ms=150.0))
    eng.register("m", model)
    q = np.asarray(x[:12], np.float32)
    ref = eng.decision(q)  # healthy baseline
    with faults.install(faults.FaultPlan.parse("serve_stall@1")) as plan:
        ticket = eng.submit(q, model="m")
        t0 = time.perf_counter()
        done = eng.drain()
        bounded = time.perf_counter() - t0
    assert plan.fired["serve_stall"] == 1, "stall never fired"
    res = done[ticket]
    assert res.verdict == "failed" and res.decision is None, res
    assert bounded < 2.0, f"watchdog not bounded: {bounded:.2f}s"
    assert eng.watchdog_trips.value == 1
    assert eng.snapshot()["per_model"]["m"]["dispatch_failures"] == 1
    # The engine keeps serving after the trip, identically.
    np.testing.assert_array_equal(eng.decision(q), ref)
    eng.close()
    print(f"[faults_smoke] watchdog tripped in {bounded:.2f}s "
          "(150 ms bound + drain), explicit 'failed' verdict, engine "
          "kept serving OK")


def check_lock_stall() -> None:
    """The lock_stall seam (ISSUE 20): DPSVM_FAULTS-armed contention
    on ModelRegistry._lock — the stall holds the registry's critical
    section while other threads hammer the same lock. With the
    committed acquired-while-holding graph acyclic (threadlint's ORDER
    contract), a held lock delays the fabric but can never wedge it:
    wall clock stays bounded, the watchdog never trips, verdicts stay
    'ok' and the answers stay right."""
    import threading

    from dpsvm_tpu.config import ServeConfig, SVMConfig
    from dpsvm_tpu.models.multiclass import train_multiclass
    from dpsvm_tpu.serving import ServingEngine
    from dpsvm_tpu.testing import faults

    rng = np.random.default_rng(9)
    x = np.concatenate([
        rng.normal(size=(60, 4)).astype(np.float32) + off
        for off in (0.0, 2.5)])
    y = np.repeat([0, 1], 60)
    model, _ = train_multiclass(
        x, y, SVMConfig(c=2.0, gamma=0.5, epsilon=1e-3), strategy="ovr")

    eng = ServingEngine(ServeConfig(buckets=(16, 64),
                                    dispatch_timeout_ms=2000.0))
    eng.register("m", model)
    q = np.asarray(x[:12], np.float32)
    ref = eng.decision(q)  # healthy baseline, seam disarmed

    stalls = 3
    os.environ["DPSVM_FAULTS"] = f"lock_stall@1x{stalls}"
    try:
        stop = threading.Event()

        def contend():
            # Read-only registry/scheduler callers — exactly who the
            # fired stall makes wait on ModelRegistry._lock.
            while not stop.is_set():
                eng.registry.get("m")
                eng.scheduler.depth_by_model()

        readers = [threading.Thread(target=contend,
                                    name=f"dpsvm-test-contend-{i}")
                   for i in range(2)]
        for th in readers:
            th.start()
        t0 = time.perf_counter()
        tickets = [eng.submit(q, model="m") for _ in range(4)]
        done = eng.drain()
        elapsed = time.perf_counter() - t0
        stop.set()
        for th in readers:
            th.join(timeout=10)
            assert not th.is_alive(), "reader wedged on the stall"
        plan = faults.active_plan()
        assert plan is not None and plan.fired["lock_stall"] >= 1, \
            "lock_stall never fired"
        fired = plan.fired["lock_stall"]
        # Bounded: the stalls serialize, they do not deadlock. Budget
        # = every fired stall back-to-back + generous slack.
        bound = fired * faults.LOCK_STALL_SECONDS + 5.0
        assert elapsed < bound, f"not bounded: {elapsed:.2f}s"
        assert eng.watchdog_trips.value == 0, \
            "lock contention must not read as a wedged dispatch"
        for t in tickets:
            res = done[t]
            assert res.verdict == "ok", res
            np.testing.assert_array_equal(res.decision, ref)
    finally:
        del os.environ["DPSVM_FAULTS"]
    eng.close()
    print(f"[faults_smoke] lock_stall fired {fired}x "
          f"({faults.LOCK_STALL_SECONDS:.2f}s each holding "
          f"ModelRegistry._lock), fabric bounded in {elapsed:.2f}s, "
          "0 watchdog trips, all verdicts ok")


def main() -> int:
    check_harness()
    check_ooc_kill_resume()
    check_ooc_mesh_kill_resume()
    check_watchdog()
    check_lock_stall()
    print("[faults_smoke] ALL OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
