"""Block-engine shape sweep on the real TPU: q x inner_iters x
pair_batch x dataset.

Measures pair-update throughput and round cost for the blockwise engine
(solver/block.py) to pick the default working-set shape. Fixed pair
budget per cell so cells are comparable; reports per-round cost (the
dispatch-floor diagnostic) and pairs/s.

The pair_batch axis ranks the batched-disjoint-pair variants (VERDICT
round-5 weak #2): the block subproblem implements pb in {1, 2, 4}
(ops/pallas_subproblem.py); pb8 exists only on the per-pair
micro-batch executor (engine='xla', solver/smo.py _run_chunk_micro) and
rides the optional --micro-pb rows.

Run: `python tools/sweep_block.py [--dataset mnist|covtype|both]
[--pair-batches 1,2,4] [--micro-pb 4,8]`.
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def make(dataset: str):
    if dataset == "mnist":
        from dpsvm_tpu.data.synth import make_mnist_like
        x, y = make_mnist_like(n=60_000, d=784, seed=7, noise=0.1)
        kw = dict(c=10.0, gamma=0.125, epsilon=0.01)
    else:
        rng = np.random.default_rng(0)
        x = (rng.normal(size=(500_000, 54)) * 0.3).astype(np.float32)
        y = np.where(x[:, 0] + 0.2 * rng.standard_normal(len(x)) > 0,
                     1, -1).astype(np.int32)
        kw = dict(c=2048.0, gamma=0.03125, epsilon=1e-3)
    return x, y, kw


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--dataset", default="both",
                    choices=["mnist", "covtype", "both"])
    ap.add_argument("--budget", type=int, default=400_000,
                    help="pair budget per cell (covtype); mnist runs to "
                    "convergence")
    ap.add_argument("--pair-batches", default="1,2,4",
                    help="comma list of block-engine pair_batch values "
                    "swept per (q, inner) cell (block supports 1/2/4)")
    ap.add_argument("--micro-pb", default="",
                    help="comma list of per-pair micro-executor "
                    "pair_batch rows to add (engine='xla'; e.g. '4,8' — "
                    "pb8 only exists there). Each row is one "
                    "engine-level cell, not a (q, inner) grid")
    args = ap.parse_args()

    from dpsvm_tpu.config import SVMConfig
    from dpsvm_tpu.solver.smo import solve

    pbs = [int(v) for v in args.pair_batches.split(",") if v]
    micro_pbs = [int(v) for v in args.micro_pb.split(",") if v]

    def run_cell(label, x, y, cfg):
        solve(x, y, cfg.replace(max_iter=64))  # compile
        best = None
        for _ in range(2):
            r = solve(x, y, cfg)
            if best is None or r.train_seconds < best.train_seconds:
                best = r
        rounds = best.stats.get("outer_rounds", 0)
        s = best.train_seconds
        print(f"  {label}: pairs={best.iterations:8d} "
              f"rounds={rounds:6d} s={s:7.3f} "
              f"pairs/s={best.iterations / s:9.0f} "
              f"ms/round={1e3 * s / max(rounds, 1):7.3f} "
              f"conv={best.converged}", flush=True)

    datasets = (["mnist", "covtype"] if args.dataset == "both"
                else [args.dataset])
    for ds in datasets:
        x, y, kw = make(ds)
        budget = args.budget if ds == "covtype" else 100_000
        print(f"== {ds}: n={len(x)} d={x.shape[1]} {kw}")
        for q in (128, 256, 512, 1024):
            for ii_mult in (1, 2, 4):
                inner = q * ii_mult
                for pb in pbs:
                    cfg = SVMConfig(**kw, engine="block",
                                    working_set_size=q,
                                    inner_iters=inner, pair_batch=pb,
                                    dtype="bfloat16", cache_lines=0,
                                    max_iter=budget)
                    run_cell(f"q={q:5d} inner={inner:5d} pb={pb}",
                             x, y, cfg)
        for pb in micro_pbs:
            # The per-pair micro executor has no (q, inner) shape; its
            # knob IS pair_batch. bf16 X halves the kernel-row read like
            # the block cells; resident Gram stays on auto (off at these
            # shapes' memory footprints).
            cfg = SVMConfig(**kw, engine="xla", pair_batch=pb,
                            dtype="bfloat16", cache_lines=0,
                            max_iter=budget)
            run_cell(f"micro pb={pb}          ", x, y, cfg)
    return 0


if __name__ == "__main__":
    sys.exit(main())
