"""Two-process jax.distributed bring-up check — the executable stand-in
for a real multi-host pod launch.

The reference's multi-rank path was only ever exercised on its real
11-host cluster (`mpirun --hostfile hf`, reference Makefile:74); its repo
ships no way to test the launcher without one. This harness starts TWO
OS processes on this machine, each with 4 virtual CPU devices, wires them
with ``initialize_multihost`` (parallel/mesh.py — the mpirun equivalent),
and verifies the cross-process SPMD semantics the distributed engines
rely on:

  * process_count/global device count (8 = 2 hosts x 4),
  * a global psum over the data mesh (the convergence pmin/pmax pattern),
  * an all_gather of per-shard values (the candidate exchange pattern),

then runs one shard_mapped distributed SMO chunk over the global mesh
with process-local input shards (jax.make_array_from_process_local_data —
how a real multi-host loader feeds solve_mesh's machinery).

Run: `python tools/multihost_check.py` (parent; spawns the 2 children).
Exit 0 = all checks passed in both processes.
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

NPROC = 2
LOCAL_DEVICES = 4


def child_main(coordinator: str, process_id: int) -> int:
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dpsvm_tpu.parallel.mesh import (DATA_AXIS, initialize_multihost,
                                         make_data_mesh, mesh_shard_map)

    initialize_multihost(coordinator, NPROC, process_id)
    assert jax.process_count() == NPROC, jax.process_count()
    n_global = len(jax.devices())
    assert n_global == NPROC * LOCAL_DEVICES, n_global
    mesh = make_data_mesh()

    # Global psum across both processes' devices (the b_hi/b_lo reduction
    # pattern of parallel/dist_smo.py and dist_block.py).
    total = jax.jit(mesh_shard_map(
        lambda v: jax.lax.psum(jnp.sum(v), DATA_AXIS), mesh=mesh,
        in_specs=P(DATA_AXIS), out_specs=P()))(
            jnp.ones((n_global,), jnp.float32))
    np.testing.assert_allclose(np.asarray(total), n_global)

    # all_gather of per-shard scalars (the working-set candidate exchange,
    # svmTrainMain.cpp:244's role) from process-LOCAL data: each process
    # contributes its own shard of the global array.
    shard = NamedSharding(mesh, P(DATA_AXIS))
    local = np.arange(n_global, dtype=np.float32).reshape(n_global, 1)[
        process_id * LOCAL_DEVICES:(process_id + 1) * LOCAL_DEVICES]
    garr = jax.make_array_from_process_local_data(shard, local,
                                                  (n_global, 1))
    gathered = jax.jit(mesh_shard_map(
        lambda v: jax.lax.all_gather(v, DATA_AXIS).reshape(-1, 1),
        mesh=mesh, in_specs=P(DATA_AXIS), out_specs=P()))(garr)
    np.testing.assert_allclose(np.asarray(gathered)[:, 0],
                               np.arange(n_global))

    # One distributed block-engine chunk over the 2-process mesh, fed with
    # process-local shards of a tiny synthetic problem.
    from dpsvm_tpu.config import SVMConfig
    from dpsvm_tpu.data.synth import make_blobs_binary
    from dpsvm_tpu.ops.kernels import KernelParams
    from dpsvm_tpu.parallel.dist_block import make_block_chunk_runner
    from dpsvm_tpu.solver.block import BlockState

    n, d = 64, 8
    x, y = make_blobs_binary(n=n, d=d, seed=0, sep=1.5)
    cfg = SVMConfig(c=1.0, gamma=0.1)
    kp = KernelParams("rbf", 0.1)

    def put(arr, spec):
        arr = np.asarray(arr)
        sh = NamedSharding(mesh, spec)
        if spec == P():
            return jax.device_put(arr, sh) if arr.ndim else jnp.asarray(arr)
        per = arr.shape[0] // NPROC
        loc = arr[process_id * per:(process_id + 1) * per]
        return jax.make_array_from_process_local_data(sh, loc, arr.shape)

    runner = make_block_chunk_runner(mesh, kp, cfg.c_bounds(), 0.001,
                                     cfg.tau, q=8, inner_iters=8,
                                     rounds_per_chunk=4)
    state = BlockState(
        alpha=put(np.zeros(n, np.float32), P(DATA_AXIS)),
        f=put((-y).astype(np.float32), P(DATA_AXIS)),
        b_hi=jnp.float32(-np.inf), b_lo=jnp.float32(np.inf),
        pairs=jnp.int32(0), rounds=jnp.int32(0))
    out = runner(put(x, P(DATA_AXIS)), put(y.astype(np.float32), P(DATA_AXIS)),
                 put(np.einsum("nd,nd->n", x, x).astype(np.float32),
                     P(DATA_AXIS)),
                 put(np.ones(n, np.float32), P(DATA_AXIS)),
                 put(np.ones(n, bool), P(DATA_AXIS)),
                 state, jnp.int32(100))
    rounds = int(out.rounds)
    pairs = int(out.pairs)
    assert rounds >= 1 and pairs >= 1, (rounds, pairs)
    print(f"[proc {process_id}] OK: {NPROC} processes, {n_global} devices, "
          f"psum/all_gather verified, block chunk ran {rounds} rounds / "
          f"{pairs} pairs", flush=True)
    return 0


def main() -> int:
    if len(sys.argv) > 1 and sys.argv[1] == "--child":
        return child_main(sys.argv[2], int(sys.argv[3]))

    from dpsvm_tpu.utils.hostenv import cleaned_cpu_env

    env = cleaned_cpu_env(LOCAL_DEVICES)  # no TPU: pure CPU bring-up check

    # Two attempts: the bind-probe-then-close port pick races with other
    # processes grabbing the port before the jax.distributed coordinator
    # binds it; a fresh port on retry removes the (rare) collision.
    for attempt in (1, 2):
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        coordinator = f"127.0.0.1:{port}"
        # Child output is captured (small — a few assert/traceback
        # lines) both to diagnose failures and to detect the
        # capability-missing case below.
        procs = [subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--child",
             coordinator, str(pid)], env=env, cwd=REPO,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
            for pid in range(NPROC)]
        outs = []
        try:
            # Well under the callers' own timeouts (tests/test_multihost.py
            # allows 1200 s total) so the finally-kill below always gets
            # to run before an outer SIGKILL would orphan the children.
            rcs = []
            for p in procs:
                out, _ = p.communicate(timeout=240)
                outs.append(out or "")
                rcs.append(p.returncode)
        except subprocess.TimeoutExpired:
            rcs = [1] * NPROC
        finally:
            for p in procs:  # never orphan a child blocked in init
                if p.poll() is None:
                    p.kill()
                    p.wait()
        if not any(rcs):
            print("MULTIHOST CHECK: PASS")
            return 0
        sys.stdout.write("".join(outs))
        if any("Multiprocess computations aren't implemented" in o
               for o in outs):
            # This jax build's CPU backend refuses cross-process
            # COMPUTATIONS (the jax.distributed bring-up itself — the
            # coordinator wiring, process_count, global device view —
            # succeeded before the first collective dispatched). A
            # missing backend capability is an environment limit, not a
            # launcher failure: report SKIP and exit clean, the same
            # contract as tools/tpu_smoke.py on a non-TPU platform.
            print("MULTIHOST CHECK: SKIP — this jax build's CPU "
                  "backend does not implement multiprocess "
                  "computations (distributed bring-up itself "
                  "succeeded)")
            return 0
        print(f"attempt {attempt}: child exit codes {rcs}"
              + ("; retrying with a fresh port" if attempt == 1 else ""))
    print("FAIL")
    return 1


if __name__ == "__main__":
    sys.exit(main())
