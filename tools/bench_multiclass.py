"""Multiclass training benchmark -> BENCH_MULTICLASS.md (VERDICT
round-4 item 2's measured artifact).

10-class MNIST-shaped data (dpsvm_tpu.data.synth.make_mnist_multiclass
— the make_mnist_like generator before its even/odd collapse), at the
reference's MNIST hyperparameters (c=10, gamma=0.125, eps=0.01,
reference Makefile:74). The reference itself cannot train this at all:
it pre-reduced MNIST to even/odd offline
(scripts/convert_mnist_to_odd_even.py).

What the table must show (the round-4 verdict's 'done' bar): end-to-end
wall ~= the sum of the per-submodel device solve times — i.e. the OvR
X re-upload per class is gone (solver/smo.py _XDEV_MEMO) and the OvO
per-pair recompiles are gone (power-of-two shape buckets, solve
pad_to). A second, executor-warm run separates one-time XLA compiles
from the steady-state cost.

Two phases so the slow CPU oracle can run while the TPU works:
  python tools/bench_multiclass.py --oracle   (sklearn OvO at the 10k
                                               anchor, writes artifacts/)
  python tools/bench_multiclass.py            (TPU runs + the artifact)
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

C, GAMMA, EPS = 10.0, 0.125, 0.01
N_FULL, N_ANCHOR, D = 60_000, 10_000, 784


def make_data(n):
    from dpsvm_tpu.data.synth import make_mnist_multiclass

    x, y = make_mnist_multiclass(n=N_FULL, d=D, seed=7, noise=0.1)
    return x[:n], y[:n]


def fleet_compare(n: int, strategy: str = "ovo",
                  fleet_size: int = 16) -> dict:
    """Sequential-vs-fleet A/B on the SAME per-pair engine config: the
    fleet executor (solver/fleet.py) must cut the device dispatch count
    ~K/ceil(K/fleet_size)-fold and collapse warm e2e toward the device
    time, while every submodel's (alpha, b, n_sv) stays parity-matched
    with its sequential solve(). Both paths run twice; the second
    (executor-warm) pass is the measured one."""
    from dpsvm_tpu.config import SVMConfig
    from dpsvm_tpu.models.multiclass import train_multiclass

    x, y = make_data(n)
    cfg = SVMConfig(c=C, gamma=GAMMA, epsilon=EPS, engine="xla",
                    cache_lines=0, fleet_size=fleet_size)

    def run(use_fleet):
        train_multiclass(x, y, cfg, strategy=strategy, backend="single",
                         use_fleet=use_fleet)  # cold: compiles
        t0 = time.perf_counter()
        m, results = train_multiclass(x, y, cfg, strategy=strategy,
                                      backend="single",
                                      use_fleet=use_fleet)
        return m, results, time.perf_counter() - t0

    _, r_seq, warm_seq = run(False)
    _, r_flt, warm_flt = run(True)
    disp_seq = sum(r.dispatches for r in r_seq)
    # Fleet dispatches are shared across a fleet's members — count each
    # fleet once (index 0), not once per submodel.
    disp_flt = sum(r.dispatches for r in r_flt
                   if r.stats["fleet"]["index"] == 0)
    db = max(abs(a.b - b.b) for a, b in zip(r_seq, r_flt))
    dsv = max(abs(a.n_sv - b.n_sv) for a, b in zip(r_seq, r_flt))
    dit = max(abs(a.iterations - b.iterations)
              for a, b in zip(r_seq, r_flt))
    da = max(float(np.max(np.abs(a.alpha - b.alpha)))
             for a, b in zip(r_seq, r_flt))
    return dict(
        n=n, strategy=strategy, models=len(r_seq),
        fleet_size=fleet_size,
        dispatches_seq=disp_seq, dispatches_fleet=disp_flt,
        dispatch_reduction=round(disp_seq / max(disp_flt, 1), 1),
        device_s_seq=round(sum(r.train_seconds for r in r_seq), 3),
        device_s_fleet=round(sum(r.train_seconds for r in r_flt), 3),
        warm_e2e_s_seq=round(warm_seq, 2),
        warm_e2e_s_fleet=round(warm_flt, 2),
        parity_max_db=round(db, 6), parity_max_dnsv=int(dsv),
        parity_max_diters=int(dit), parity_max_dalpha=round(da, 6),
        # The existing parity bar: |b - b_ref| < 5e-3 (tests) with SV
        # counts within 2% (bench.py's gate).
        parity_ok=bool(db < 5e-3
                       and dsv <= max(2, 0.02 * max(r.n_sv
                                                    for r in r_seq))),
    )


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--oracle", action="store_true")
    ap.add_argument("--quick", type=int, default=0, metavar="N",
                    help="run ONLY the sequential-vs-fleet comparison at "
                         "this n (any backend; prints JSON, writes no "
                         "artifact) — the CPU-checkable slice of the "
                         "benchmark")
    args = ap.parse_args()
    if args.quick:
        for strat in ("ovr", "ovo"):
            print(json.dumps(fleet_compare(args.quick, strat)), flush=True)
        return 0
    outdir = os.path.join(REPO, "artifacts")
    os.makedirs(outdir, exist_ok=True)
    opath = os.path.join(outdir, "oracle_multiclass10k.json")

    if args.oracle:
        from sklearn.svm import SVC

        x, y = make_data(N_ANCHOR)
        t0 = time.perf_counter()
        sk = SVC(C=C, gamma=GAMMA, tol=EPS, cache_size=4000).fit(x, y)
        secs = time.perf_counter() - t0
        summary = dict(n=N_ANCHOR, n_sv=int(sk.n_support_.sum()),
                       acc=float(sk.score(x, y)), seconds=round(secs, 1))
        with open(opath, "w") as fh:
            json.dump(summary, fh)
        print(f"[oracle] {json.dumps(summary)}", flush=True)
        return 0

    import jax

    from dpsvm_tpu.config import SVMConfig
    from dpsvm_tpu.models.multiclass import (accuracy_multiclass,
                                             train_multiclass)

    with open(opath) as fh:
        oracle = json.load(fh)

    cfg = SVMConfig(c=C, gamma=GAMMA, epsilon=EPS, engine="block",
                    working_set_size=256, cache_lines=0)

    def run(n, strategy):
        x, y = make_data(n)
        # Cold pass: includes every XLA compile + the one X upload.
        t0 = time.perf_counter()
        m, results = train_multiclass(x, y, cfg, strategy=strategy,
                                      backend="single")
        cold = time.perf_counter() - t0
        # Warm pass: executors cached -> end-to-end is transfers +
        # dispatches + host glue. THIS is the number the 'e2e ~= sum of
        # solve times' bar judges.
        t0 = time.perf_counter()
        m, results = train_multiclass(x, y, cfg, strategy=strategy,
                                      backend="single")
        warm = time.perf_counter() - t0
        dev = sum(r.train_seconds for r in results)
        t0 = time.perf_counter()
        acc = accuracy_multiclass(m, x, y)
        pred_s = time.perf_counter() - t0
        conv = sum(r.converged for r in results)
        row = dict(n=n, strategy=strategy, models=len(results),
                   converged=conv, device_s=round(dev, 3),
                   warm_e2e_s=round(warm, 2), cold_e2e_s=round(cold, 2),
                   train_acc=round(float(acc), 4),
                   predict_s=round(pred_s, 2))
        print(json.dumps(row), flush=True)
        return row

    rows = [run(N_ANCHOR, "ovr"), run(N_ANCHOR, "ovo"),
            run(N_FULL, "ovr"), run(N_FULL, "ovo")]

    # Fleet A/B (the dispatch-count story): the 45-submodel OvO is the
    # headline case — 45 sequential per-pair solves vs ceil(45/16) = 3
    # fleet dispatch sequences.
    fleet_rows = [fleet_compare(N_ANCHOR, "ovo"),
                  fleet_compare(N_FULL, "ovo")]
    for fr in fleet_rows:
        print(json.dumps(fr), flush=True)

    dev = str(jax.devices()[0])
    lines = [
        "# BENCH_MULTICLASS — 10-class MNIST-shaped training",
        "",
        "Command: `python tools/bench_multiclass.py` (real TPU; "
        "generator `make_mnist_multiclass(n=60000, d=784, seed=7, "
        "noise=0.1)`, hyperparameters from the reference's MNIST run, "
        "reference Makefile:74). The reference cannot train multiclass "
        "at all — it pre-reduced MNIST to even/odd offline "
        "(scripts/convert_mnist_to_odd_even.py); this artifact measures "
        "the capability extension at the reference's own scale.",
        "",
        f"* device: {dev}",
        f"* sklearn oracle (n={oracle['n']} anchor, same generator/"
        f"hyperparameters): train accuracy {oracle['acc']:.4f}, "
        f"{oracle['n_sv']} SVs, fit in {oracle['seconds']:.0f} s "
        "(single-core LibSVM OvO)",
        "",
        "| n | strategy | submodels | converged | device solve s (sum) |"
        " warm e2e s | cold e2e s | train acc | predict s |",
        "|---|---|---|---|---|---|---|---|---|",
    ]
    for r in rows:
        lines.append(
            f"| {r['n']} | {r['strategy']} | {r['models']} | "
            f"{r['converged']}/{r['models']} | {r['device_s']} | "
            f"{r['warm_e2e_s']} | {r['cold_e2e_s']} | {r['train_acc']} | "
            f"{r['predict_s']} |")
    a_ovr, a_ovo = rows[0], rows[1]
    lines += [
        "",
        f"Accuracy parity at the oracle-tractable anchor: ovr "
        f"{a_ovr['train_acc']} / ovo {a_ovo['train_acc']} vs sklearn "
        f"{oracle['acc']:.4f}.",
        "",
        "Reading the e2e columns: warm e2e minus the device column is "
        "host glue (label remaps, subset copies, result assembly) plus "
        "transfers and PER-DISPATCH TUNNEL LATENCY — OvR uploads X ONCE "
        "(solver/smo.py _XDEV_MEMO) and OvO compiles per power-of-two "
        "bucket, not per subset shape (solve pad_to). On this harness "
        "the device sits behind a WAN tunnel whose round-trips cost "
        "0.3-1.5 s depending on the hour; each of OvO's 45 sequential "
        "solves makes ~8 of them (transfers, dispatch, result pulls), "
        "so the 60k OvO warm e2e is dominated by ~360 tunnel "
        "round-trips, not by anything the framework computes — on "
        "locally-attached TPUs those are sub-ms. The device column is "
        "the hardware-honest number (the same timer discipline as every "
        "artifact, solver/smo.py).",
        "",
        "Prediction is ONE stacked dispatch per query block for ALL "
        "submodels (models/multiclass.py _stacked_decision: shared "
        "power-of-two SV bucket, (k, nb, m) batched einsum): the "
        "45-model OvO predict at n=10k measured 244 s as 90 per-model "
        "dispatches and 9.0 s stacked (27x); n=60k: 697 -> 28.5 s.",
        "",
        "## Fleet training: all submodels per dispatch sequence",
        "",
        "The TRAINING analog of the stacked predict (solver/fleet.py): "
        "OvO's 45 subproblems ride the shared X as row masks, stacked "
        "along a leading axis and trained inside ONE compiled "
        "while_loop per fleet of "
        f"{fleet_rows[0]['fleet_size']} (per-problem convergence "
        "masking freezes finished submodels while stragglers run). "
        "Sequential-vs-fleet on the SAME per-pair engine config, "
        "executor-warm, parity bar |db| < 5e-3:",
        "",
        "| n | submodels | dispatches seq -> fleet | reduction | "
        "warm e2e s seq -> fleet | device s seq -> fleet | "
        "max |db| | max dSV | parity |",
        "|---|---|---|---|---|---|---|---|---|",
    ] + [
        f"| {fr['n']} | {fr['models']} | {fr['dispatches_seq']} -> "
        f"{fr['dispatches_fleet']} | {fr['dispatch_reduction']}x | "
        f"{fr['warm_e2e_s_seq']} -> {fr['warm_e2e_s_fleet']} | "
        f"{fr['device_s_seq']} -> {fr['device_s_fleet']} | "
        f"{fr['parity_max_db']} | {fr['parity_max_dnsv']} | "
        f"{'OK' if fr['parity_ok'] else 'FAIL'} |"
        for fr in fleet_rows
    ] + [
        "",
    ]
    path = os.path.join(REPO, "BENCH_MULTICLASS.md")
    with open(path, "w") as fh:
        fh.write("\n".join(lines))
    print(f"wrote {path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
