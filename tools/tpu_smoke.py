"""On-device (real TPU) smoke checks for the Pallas kernels.

The pytest suite runs everything on CPU (interpret mode for Pallas), so
real Mosaic lowering is only otherwise exercised by bench.py's single
q=128 mvp configuration. This script drives the lowering-sensitive
surface on the actual chip:

  * solve_subproblem_pallas for every pairing rule (mvp / second_order /
    nu) x q in {16, 40, 128} — small and non-lane-aligned q included
    (solve/solve_mesh auto-select the Pallas inner for arbitrary even q);
  * one end-to-end block-engine solve per rule (the inner_impl="pallas"
    path of solver/block.py run_chunk_block);
  * one fused per-pair Pallas engine solve (ops/pallas_fused.py).

Each Pallas result is compared against the XLA implementation of the same
computation. Exits nonzero on any mismatch. Run via `make tpu_smoke`
(needs the axon TPU free — one client process at a time).

Every run writes a TPU_SMOKE_r<NN>.json artifact at the repo root (per-
check name/status/metrics + device + timestamp) — the evidence lives in
a versioned file, not in a commit message's prose (VERDICT round-5
weak #5).
"""

from __future__ import annotations

import glob
import json
import os
import re
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def write_artifact(device: str, checks: list, failures: int) -> str:
    """TPU_SMOKE_r<NN>.json with NN = 1 + the highest existing round
    (the MULTICHIP_r*.json / BENCH_r*.json numbering convention).
    Carries the shared telemetry schema_version (dpsvm_tpu/obs/runlog)
    like every other benchmark artifact, and — when the telemetry
    spine is enabled (DPSVM_OBS=1) — mirrors the checks into a
    tpu_smoke run log so device sessions leave the same JSONL trail
    the solver and serving runs do."""
    from dpsvm_tpu.obs import obs_enabled
    from dpsvm_tpu.obs.runlog import SCHEMA_VERSION, RunLog

    rounds = []
    for p in glob.glob(os.path.join(REPO, "TPU_SMOKE_r*.json")):
        m = re.search(r"_r(\d+)\.json$", p)
        if m:
            rounds.append(int(m.group(1)))
    rn = max(rounds, default=0) + 1
    path = os.path.join(REPO, f"TPU_SMOKE_r{rn:02d}.json")
    with open(path, "w") as fh:
        json.dump({
            "device": device,
            "utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            "result": "PASS" if failures == 0 else f"{failures} FAILURES",
            "schema_version": SCHEMA_VERSION,
            "checks": checks,
        }, fh, indent=1)
    if obs_enabled():
        with RunLog.open("tpu_smoke",
                         meta={"device": device,
                               "artifact": os.path.basename(path)}) as rl:
            for c in checks:
                rl.record("event", **c)
            rl.finish(result="PASS" if failures == 0
                      else f"{failures} FAILURES",
                      checks=len(checks))
    return path


def main() -> int:
    import jax
    import jax.numpy as jnp

    dev = jax.devices()[0]
    if dev.platform != "tpu":
        print(f"SKIP: first device is {dev.platform!r}, not tpu")
        return 0
    print(f"device: {dev.device_kind}")

    checks: list = []

    def record(name: str, ok: bool, **extra) -> None:
        checks.append(dict(name=name, status="OK" if ok else "FAIL",
                           **extra))

    from dpsvm_tpu.config import SVMConfig
    from dpsvm_tpu.data.synth import make_blobs_binary
    from dpsvm_tpu.ops.kernels import KernelParams, kernel_matrix
    from dpsvm_tpu.ops.pallas_subproblem import solve_subproblem_pallas
    from dpsvm_tpu.solver.block import _solve_subproblem, select_block
    from dpsvm_tpu.solver.smo import solve

    cfg = SVMConfig(c=5.0, gamma=0.2, epsilon=1e-3)
    kp = KernelParams("rbf", cfg.gamma)
    x, y = make_blobs_binary(n=300, d=10, seed=3, sep=1.2)
    rng = np.random.default_rng(1)
    alpha = np.clip(rng.normal(0.5, 0.5, 300), 0, cfg.c).astype(np.float32)
    K = np.asarray(kernel_matrix(x, x, kp))
    f = ((alpha * y) @ K - y).astype(np.float32)

    failures = 0
    for rule in ("mvp", "second_order", "nu"):
        for q in (16, 40, 128):
            w, ok, _, _ = select_block(jnp.asarray(f), jnp.asarray(alpha),
                                       jnp.asarray(y, jnp.float32), cfg.c,
                                       q, rule=rule)
            w_np = np.asarray(w)
            kb_w = jnp.asarray(K[np.ix_(w_np, w_np)].astype(np.float32))
            kd_w = jnp.asarray(np.diag(K)[w_np].astype(np.float32))
            a_w = jnp.asarray(alpha[w_np])
            y_w = jnp.asarray(y[w_np].astype(np.float32))
            f_w = jnp.asarray(f[w_np])
            for pb in ((1, 2, 4) if rule == "mvp" else (1,)):
                a_x, _, t_x = _solve_subproblem(
                    kb_w, kd_w, ok, a_w, y_w, f_w, cfg.c, cfg.epsilon,
                    cfg.tau, jnp.int32(64), rule=rule, pair_batch=pb)
                a_p, t_p = solve_subproblem_pallas(
                    kb_w, a_w, y_w, f_w, kd_w, ok.astype(jnp.float32),
                    jnp.int32(64), cfg.c, cfg.epsilon, cfg.tau, rule=rule,
                    pair_batch=pb)
                same_t = int(t_x) == int(t_p)
                close = np.allclose(np.asarray(a_x), np.asarray(a_p),
                                    rtol=1e-5, atol=1e-6)
                status = "OK" if (same_t and close) else "FAIL"
                failures += status == "FAIL"
                record(f"subproblem/{rule}/q{q}/pb{pb}",
                       same_t and close, pairs=int(t_p))
                print(f"subproblem rule={rule:13s} q={q:4d} pb={pb} "
                      f"pairs={int(t_p):3d} {status}")

    # End-to-end block solves on device (inner_impl='pallas' path).
    r_ref = solve(x, y, cfg)
    for rule in ("mvp", "second_order"):
        r = solve(x, y, cfg.replace(engine="block", working_set_size=40,
                                    selection=rule))
        db = abs(r.b - r_ref.b)
        status = "OK" if (r.converged and db < 5e-2) else "FAIL"
        failures += status == "FAIL"
        record(f"block/selection={rule}", r.converged and db < 5e-2,
               pairs=int(r.iterations), db=round(db, 5))
        print(f"block-engine selection={rule:13s} pairs={r.iterations} "
              f"|b-b_ref|={db:.4f} {status}")
    for pb in (2, 4):
        r2 = solve(x, y, cfg.replace(engine="block", working_set_size=40,
                                     pair_batch=pb))
        db2 = abs(r2.b - r_ref.b)
        status = "OK" if (r2.converged and db2 < 5e-2) else "FAIL"
        failures += status == "FAIL"
        record(f"block/pb{pb}", r2.converged and db2 < 5e-2,
               pairs=int(r2.iterations), db=round(db2, 5))
        print(f"block-engine pair_batch={pb}    pairs={r2.iterations} "
              f"|b-b_ref|={db2:.4f} {status}")
    # Per-pair micro-batch executor (solver/smo.py _run_chunk_micro):
    # approx_max_k + unrolled dynamic slices must legalize on Mosaic/XLA
    # TPU, and the stale-rank semantics must land on the same optimum.
    for pb in (4, 8):
        rm = solve(x, y, cfg.replace(engine="xla", pair_batch=pb))
        dbm = abs(rm.b - r_ref.b)
        status = "OK" if (rm.converged and dbm < 5e-2) else "FAIL"
        failures += status == "FAIL"
        record(f"micro/pb{pb}", rm.converged and dbm < 5e-2,
               pairs=int(rm.iterations), db=round(dbm, 5))
        print(f"micro-batch pair_batch={pb}    pairs={rm.iterations} "
              f"|b-b_ref|={dbm:.4f} {status}")
    from dpsvm_tpu.models.nusvm import train_nusvc

    m1, _ = train_nusvc(x, y, nu=0.3, config=cfg)
    mb, rb = train_nusvc(x, y, nu=0.3,
                         config=cfg.replace(engine="block",
                                            working_set_size=40))
    from dpsvm_tpu.predict import decision_function

    dd = float(np.max(np.abs(decision_function(m1, x)
                             - decision_function(mb, x))))
    status = "OK" if (rb.converged and dd < 0.1) else "FAIL"
    failures += status == "FAIL"
    record("block/nu-svc", rb.converged and dd < 0.1, ddec=round(dd, 5))
    print(f"block-engine nu-svc max|ddec|={dd:.4f} {status}")

    # Fused fold+select block rounds (ops/pallas_fold_select.py): real
    # Mosaic lowering of the fold kernel + per-row candidate assembly,
    # plain and Kahan-compensated. Needs n >= 64*q so every slot can
    # find a per-128-row candidate (smaller n auto-falls-back).
    xf, yf = make_blobs_binary(n=4096, d=24, seed=5, sep=1.2)
    rf_ref = solve(xf, yf, cfg.replace(engine="block",
                                       working_set_size=32,
                                       fused_fold=False))
    fused_runs = {}
    for comp in (False, True):
        rf = solve(xf, yf, cfg.replace(engine="block", working_set_size=32,
                                       fused_fold=True, compensated=comp,
                                       matmul_precision="default"))
        fused_runs[comp] = rf
        db = abs(rf.b - rf_ref.b)
        status = "OK" if (rf.converged and db < 5e-2) else "FAIL"
        failures += status == "FAIL"
        record(f"fused_fold/compensated={comp}",
               rf.converged and db < 5e-2, pairs=int(rf.iterations),
               db=round(db, 5))
        print(f"fused fold+select compensated={comp} pairs={rf.iterations} "
              f"|b-b_ref|={db:.4f} {status}")

    # One-HBM-pass fused round (ISSUE 12, config.fused_round): first
    # real Mosaic lowering of ops/pallas_round.py — the scalar-prefetch
    # grid, the in-kernel dynamic-slice row gather from HBM, the
    # revisited (q, q) Gram output block and the in-register fold
    # contraction. Gated on optimum quality; the bitwise field vs the
    # stock fused engine is recorded informationally (the bit-identity
    # CONTRACT is pinned on the CPU harness where both engines execute
    # the identical scalar ops — real-MXU tiling may legitimately
    # regroup the accumulations).
    for comp in (False, True):
        rfr = solve(xf, yf, cfg.replace(engine="block",
                                        working_set_size=32,
                                        fused_round=True,
                                        compensated=comp,
                                        matmul_precision="default"))
        db = abs(rfr.b - rf_ref.b)
        bitwise = bool(np.array_equal(rfr.alpha, fused_runs[comp].alpha)
                       and rfr.iterations == fused_runs[comp].iterations)
        status = "OK" if (rfr.converged and db < 5e-2) else "FAIL"
        failures += status == "FAIL"
        record(f"fused_round/compensated={comp}",
               rfr.converged and db < 5e-2, pairs=int(rfr.iterations),
               db=round(db, 5), bitwise_vs_fused_fold=bitwise)
        print(f"one-pass fused round compensated={comp} "
              f"pairs={rfr.iterations} |b-b_ref|={db:.4f} "
              f"bitwise={bitwise} {status}")

    # Mesh fused fold+select on the single real chip (1-device mesh:
    # exercises the shard_mapped pallas_call lowering + gathered top-h).
    from dpsvm_tpu.parallel.dist_smo import solve_mesh

    rm = solve_mesh(xf, yf, cfg.replace(engine="block",
                                        working_set_size=32,
                                        fused_fold=True,
                                        matmul_precision="default"),
                    num_devices=1)
    db = abs(rm.b - rf_ref.b)
    status = "OK" if (rm.converged and db < 5e-2) else "FAIL"
    failures += status == "FAIL"
    record("mesh/fused_fold", rm.converged and db < 5e-2,
           pairs=int(rm.iterations), db=round(db, 5))
    print(f"mesh fused fold+select pairs={rm.iterations} "
          f"|b-b_ref|={db:.4f} {status}")

    # Pipelined block rounds (ISSUE 2): real Mosaic lowering of the
    # pre-fold selection kernel (ops/pallas_fold_select.py select_rows
    # — engaged automatically on TPU at this padded shape) + the
    # handoff-gated round body, plain and compensated, then the mesh
    # runner's overlapped-collective round on the 1-device mesh.
    for comp in (False, True):
        rp = solve(xf, yf, cfg.replace(engine="block",
                                       working_set_size=32,
                                       pipeline_rounds=True,
                                       compensated=comp,
                                       matmul_precision="default"))
        db = abs(rp.b - rf_ref.b)
        status = "OK" if (rp.converged and db < 5e-2) else "FAIL"
        failures += status == "FAIL"
        record(f"pipelined/compensated={comp}",
               rp.converged and db < 5e-2, pairs=int(rp.iterations),
               db=round(db, 5))
        print(f"pipelined rounds compensated={comp} pairs="
              f"{rp.iterations} |b-b_ref|={db:.4f} {status}")
    rpm = solve_mesh(xf, yf, cfg.replace(engine="block",
                                         working_set_size=32,
                                         pipeline_rounds=True,
                                         matmul_precision="default"),
                     num_devices=1)
    db = abs(rpm.b - rf_ref.b)
    status = "OK" if (rpm.converged and db < 5e-2) else "FAIL"
    failures += status == "FAIL"
    record("mesh/pipelined", rpm.converged and db < 5e-2,
           pairs=int(rpm.iterations), db=round(db, 5))
    print(f"mesh pipelined rounds pairs={rpm.iterations} "
          f"|b-b_ref|={db:.4f} {status}")

    # Shard-parallel working sets (ISSUE 4): real Mosaic/XLA:TPU
    # lowering of the shard-local round (local select_block + Pallas
    # subproblem), the per-sync touched-rows all_gather + fold, and the
    # host-side endgame demotion back to the exact global runner — on
    # the 1-device mesh (the P=1 degenerate case must still land on the
    # optimum; the throughput claim is --shardlocal's, not this check's).
    rsl = solve_mesh(xf, yf, cfg.replace(engine="block",
                                         working_set_size=32,
                                         local_working_sets=2,
                                         sync_rounds=2,
                                         matmul_precision="default"),
                     num_devices=1)
    db = abs(rsl.b - rf_ref.b)
    status = "OK" if (rsl.converged and db < 5e-2) else "FAIL"
    failures += status == "FAIL"
    record("mesh/shardlocal", rsl.converged and db < 5e-2,
           pairs=int(rsl.iterations), db=round(db, 5),
           demoted=bool(rsl.stats.get("shardlocal_demoted")))
    print(f"mesh shard-local working sets pairs={rsl.iterations} "
          f"|b-b_ref|={db:.4f} "
          f"demoted={rsl.stats.get('shardlocal_demoted')} {status}")

    # Ring-overlapped candidate exchange (ISSUE 11): the first real
    # exercise of ops/ring.py's make_async_remote_copy path outside
    # interpret mode — Mosaic lowering of the DMA ring + barrier, and
    # the bit-identity claim (tests/test_ring.py pinned it in interpret
    # mode; a real-ICI divergence would surface HERE first). Needs >= 2
    # devices; single-chip sessions record the skip explicitly.
    n_dev_all = len(jax.devices())
    if n_dev_all >= 2:
        ring_cfg = cfg.replace(engine="block", working_set_size=32,
                               matmul_precision="default")
        rg0 = solve_mesh(xf, yf, ring_cfg.replace(ring_exchange=False),
                         num_devices=n_dev_all)
        rg1 = solve_mesh(xf, yf, ring_cfg.replace(ring_exchange=True),
                         num_devices=n_dev_all)
        bitwise = bool(np.array_equal(rg0.alpha, rg1.alpha)
                       and rg0.iterations == rg1.iterations)
        db = abs(rg1.b - rf_ref.b)
        ok = rg1.converged and bitwise and db < 5e-2
        failures += not ok
        record("mesh/ring_exchange", ok, pairs=int(rg1.iterations),
               bitwise_vs_gather=bitwise, db=round(db, 5),
               n_devices=n_dev_all)
        print(f"ring exchange P={n_dev_all} pairs={rg1.iterations} "
              f"bitwise={bitwise} |b-b_ref|={db:.4f} "
              f"{'OK' if ok else 'FAIL'}")
        rsr = solve_mesh(xf, yf,
                         ring_cfg.replace(ring_exchange=True,
                                          local_working_sets=2,
                                          sync_rounds=2),
                         num_devices=n_dev_all)
        db = abs(rsr.b - rf_ref.b)
        ok = rsr.converged and db < 5e-2
        failures += not ok
        record("mesh/ring_shardlocal", ok, pairs=int(rsr.iterations),
               db=round(db, 5),
               demoted=bool(rsr.stats.get("shardlocal_demoted")))
        print(f"ring shard-local sync pairs={rsr.iterations} "
              f"|b-b_ref|={db:.4f} {'OK' if ok else 'FAIL'}")
    else:
        record("mesh/ring_exchange", True, skipped=True,
               reason="needs >= 2 devices")
        print("ring exchange: SKIP (single-device session)")

    # bf16 Gram gate (ISSUE 11): the perturbation bound's verdict on
    # the smoke data plus one accept-path solve — bf16 X storage with
    # f32 accumulation must legalize on real XLA:TPU and stay within
    # the quality envelope the gate promises.
    rbg = solve(xf, yf, cfg.replace(engine="block", working_set_size=32,
                                    bf16_gram=True,
                                    matmul_precision="default"))
    bfg = rbg.stats["bf16_gram"]
    db = abs(rbg.b - rf_ref.b)
    ok = rbg.converged and (db < 5e-2 if bfg["active"] else db < 5e-3)
    failures += not ok
    record("bf16_gram", ok, active=bool(bfg["active"]),
           risk=bfg["risk"], pairs=int(rbg.iterations), db=round(db, 5))
    print(f"bf16 gram gate active={bfg['active']} risk={bfg['risk']} "
          f"pairs={rbg.iterations} |b-b_ref|={db:.4f} "
          f"{'OK' if ok else 'FAIL'}")

    # Fused per-pair Pallas engine.
    r_pl = solve(x, y, cfg.replace(engine="pallas"))
    db = abs(r_pl.b - r_ref.b)
    status = "OK" if (r_pl.converged and db < 5e-3) else "FAIL"
    failures += status == "FAIL"
    record("pallas_engine", r_pl.converged and db < 5e-3,
           iters=int(r_pl.iterations), db=round(db, 6))
    print(f"pallas per-pair engine iters={r_pl.iterations} "
          f"|b-b_ref|={db:.5f} {status}")

    # Fleet executor (solver/fleet.py): the batched selection
    # (argmin/argmax over a (k, n) stack), the 2k unrolled dynamic
    # slices and the (k, n) rank-2 fold must legalize on real XLA:TPU,
    # and a mixed fleet (full problem + masked subset + per-problem C)
    # must land on the sequential optima.
    from dpsvm_tpu.solver.fleet import FleetProblem, solve_fleet

    mask = np.arange(len(y)) < 200
    fleet = solve_fleet(x, [
        FleetProblem(y=y),
        FleetProblem(y=y, row_mask=mask),
        FleetProblem(y=y, c=2.0 * cfg.c),
    ], cfg)
    seq = [solve(x, y, cfg),
           solve(x[mask], y[mask], cfg),
           solve(x, y, cfg.replace(c=2.0 * cfg.c))]
    for name, rf2, rs in zip(("full", "masked", "c-swept"), fleet, seq):
        dbf = abs(rf2.b - rs.b)
        ok = rf2.converged and dbf < 5e-3
        failures += not ok
        record(f"fleet/{name}", ok, iters=int(rf2.iterations),
               db=round(dbf, 6),
               dispatches=int(rf2.dispatches))
        print(f"fleet {name:8s} iters={rf2.iterations} "
              f"|b-b_seq|={dbf:.5f} {'OK' if ok else 'FAIL'}")

    print("TPU SMOKE:", "PASS" if failures == 0 else f"{failures} FAILURES")
    path = write_artifact(str(dev.device_kind), checks, failures)
    print(f"artifact: {path}")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
