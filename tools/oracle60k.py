"""One-time LibSVM oracle run at the reference's full MNIST scale.

The reference's headline correctness claim is "same number of Support
Vectors as LibSVM" on MNIST even-odd 60000x784 (reference README.md:27,
config reference Makefile:74). tools/parity.py checks that claim at
n=10000 (the sklearn oracle at 60k is hours — LibSVM's real-MNIST run
took 13,963 s, reference README.md:25); this script runs the oracle ONCE
at the full n=60000 on the benchmark dataset (make_mnist_like seed=7
noise=0.1) at eps=0.001 (the tolerance of the reference's parity claim)
and saves everything tools/parity60k_report.py needs to write the
PARITY.md section:

    artifacts/oracle60k.npz   alpha (n,), dec (n,), y (n,)
    artifacts/oracle60k.json  {n_sv, merged_sv, seconds, acc, params}

Pure CPU (sklearn) — safe to run concurrently with TPU work.
Run: `python tools/oracle60k.py` (expect hours; nohup it).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tools.parity_common import merged_sv as merged_sv_count

N, D, SEED, NOISE = 60_000, 784, 7, 0.1
C, GAMMA, EPS = 10.0, 0.125, 0.001


def main() -> int:
    from sklearn.svm import SVC

    from dpsvm_tpu.data.synth import make_mnist_like

    outdir = os.path.join(REPO, "artifacts")
    os.makedirs(outdir, exist_ok=True)
    x, y = make_mnist_like(n=N, d=D, seed=SEED, noise=NOISE)
    print(f"[oracle60k] fitting SVC(C={C}, gamma={GAMMA}, tol={EPS}) "
          f"on {N}x{D} ...", flush=True)
    t0 = time.perf_counter()
    sk = SVC(C=C, gamma=GAMMA, tol=EPS, cache_size=8000).fit(x, y)
    seconds = time.perf_counter() - t0
    alpha = np.zeros(N)
    alpha[sk.support_] = np.abs(sk.dual_coef_[0])
    dec = sk.decision_function(x)
    acc = float(sk.score(x, y))
    n_sv = int(sk.n_support_.sum())
    msv = merged_sv_count(x, y, alpha)
    np.savez(os.path.join(outdir, "oracle60k.npz"), alpha=alpha, dec=dec, y=y)
    summary = dict(n=N, d=D, seed=SEED, noise=NOISE, c=C, gamma=GAMMA,
                   eps=EPS, n_sv=n_sv, merged_sv=msv, acc=acc,
                   seconds=round(seconds, 1))
    with open(os.path.join(outdir, "oracle60k.json"), "w") as fh:
        json.dump(summary, fh, indent=1)
    print(f"[oracle60k] done: {json.dumps(summary)}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
