"""Inference-throughput artifact: the svmTest role, timed.

The reference's test program (seq_test.cpp:187-210) scores each point with
an O(n_sv * d) CBLAS loop on one CPU core and publishes no timing. Here
the same computation is one (n_test, d) x (d, n_sv) MXU matmul chain
(dpsvm_tpu/predict.py); this tool measures it at the reference's two test
shapes (MNIST 10k x 784, Adult 16281 x 123) against models with the SV
counts the parity harness produced (PARITY.md), and REWRITES
BENCH_PREDICT.md with one JSON line per shape (the artifact records the
current build; history lives in git).

Run on the real TPU: `python tools/bench_predict.py`.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

SHAPES = [
    # name, n_test, d, n_sv (parity-harness scale), reference anchor
    ("mnist-test-shaped", 10_000, 784, 3364, "reference Makefile:80"),
    ("adult-test-shaped", 16_281, 123, 11905, "reference Makefile:83"),
]


def main() -> int:
    import jax
    import jax.numpy as jnp

    from dpsvm_tpu.models.svm_model import SVMModel
    from dpsvm_tpu.ops.kernels import KernelParams
    from dpsvm_tpu.predict import _decision_batch

    dev = jax.devices()[0]
    rng = np.random.default_rng(3)
    lines = []
    for name, n_test, d, n_sv, anchor in SHAPES:
        kp = KernelParams("rbf", 0.125 if d == 784 else 0.5)
        model = SVMModel(
            sv_x=rng.random((n_sv, d), np.float32),
            sv_alpha=rng.random(n_sv).astype(np.float32),
            sv_y=np.where(rng.random(n_sv) < 0.5, 1, -1).astype(np.int32),
            b=0.1,
            kernel=kp)
        # DEVICE time only: queries and SVs staged to HBM outside the
        # timer (this dev harness reaches the chip over a tunnel whose
        # ~15 MB/s upload would otherwise be the whole measurement; a
        # real deployment pays PCIe/ICI, and the reference's CPU tester
        # has no transfer at all).
        q = jnp.asarray(rng.random((n_test, d), np.float32))
        sv_x = jnp.asarray(model.sv_x)
        coef = jnp.asarray(model.dual_coef)
        b = jnp.float32(model.b)
        # Per-execution time by DIFFERENCING two in-dispatch rep counts
        # ((t_hi - t_lo) / (hi - lo)): the tunnel adds tens of ms of
        # fixed per-dispatch latency, and single executions on repeated
        # identical dispatches can return in ~60 us (served without
        # re-execution), so neither a lone call nor one rep count is
        # trustworthy. The summed-decision carry keeps the full batch
        # live (a sliced carry lets XLA compute one kernel row instead),
        # and the acc*1e-30 term chains the trips.
        LO, HI = 50, 500

        def make_loop(reps):
            @jax.jit
            def loop(q, sv_x, coef, b):
                def body(t, acc):
                    dec = _decision_batch(q + acc * 1e-30, sv_x, coef, b,
                                          kp)
                    return jnp.sum(dec)
                return jax.lax.fori_loop(0, reps, body, jnp.float32(0))
            return loop

        lo_fn, hi_fn = make_loop(LO), make_loop(HI)
        # Timing discipline for this tunneled harness (each clause is a
        # measured failure mode of a simpler formulation): the pipeline
        # is drained by a VALUE PULL before the clock starts and the
        # timed region ends with a value pull of the result —
        # block_until_ready alone returns in ~60 us with the work still
        # queued; every call gets distinct input contents; the fixed
        # pull/dispatch latency cancels in the LO/HI difference.
        qs = [q + jnp.float32(k * 1e-6) for k in range(7)]
        float(lo_fn(qs[0], sv_x, coef, b))  # compile + sync
        float(hi_fn(qs[0], sv_x, coef, b))
        t_lo = min(_timed(lo_fn, (qs[k], sv_x, coef, b))
                   for k in (1, 2, 3))
        t_hi = min(_timed(hi_fn, (qs[k], sv_x, coef, b))
                   for k in (4, 5, 6))
        best = max((t_hi - t_lo) / (HI - LO), 1e-9)
        # Sanity gate: a per-execution time implying more than the
        # chip's bf16 peak means the measurement collapsed (cache /
        # dead-code) — fail loudly rather than publish nonsense.
        flops = 2.0 * n_test * d * n_sv
        if flops / best > 400e12:
            raise RuntimeError(
                f"{name}: measured {flops / best / 1e12:.0f} TFLOP/s "
                "> v5e peak; timing collapsed")
        rec = {
            "metric": f"{name} batched RBF decision function, "
                      f"{n_test}x{d} against {n_sv} SVs ({anchor}; the "
                      "reference's CPU tester publishes no timing)",
            "value": round(best, 4),
            "unit": "seconds",
            "examples_per_second": round(n_test / best),
            "device": str(dev),
        }
        print(json.dumps(rec))
        lines.append(rec)

    with open(os.path.join(REPO, "BENCH_PREDICT.md"), "w") as fh:
        fh.write("# BENCH_PREDICT — batched inference throughput\n\n"
                 "Command: `python tools/bench_predict.py` (real TPU; "
                 "device time per execution via in-dispatch rep-count "
                 "differencing, value-pull-synced, best of 3; synthetic "
                 "SV sets at PARITY.md's oracle SV counts)."
                 "\n\n```json\n"
                 + "\n".join(json.dumps(r) for r in lines)
                 + "\n```\n")
    return 0


def _timed(fn, args) -> float:
    import jax.numpy as jnp

    float(jnp.sum(args[0]))  # drain the dispatch pipeline
    t0 = time.perf_counter()
    float(fn(*args))  # dispatch + value pull = full sync
    return time.perf_counter() - t0


if __name__ == "__main__":
    sys.exit(main())
