"""tpulint — static HLO/jaxpr contract linter (ISSUE 5).

Lowers the manifest of hot entrypoints (dpsvm_tpu/analysis/manifest.py)
at canonical shapes on the CPU backend, extracts structured facts
(collective ops + payload bytes, dispatch counts, host transfers,
dtype-promotion leaks, rank-3 kernel products, donation misses,
recompile hazards), and diffs them against the checked-in budgets in
dpsvm_tpu/analysis/budgets/*.json.

`--threads` switches to the threadlint surface (ISSUE 20): the same
deny-by-default contract discipline pointed at the serving layer's
CONCURRENCY instead of its HLO — guarded-by, lock-order, thread-
lifecycle, and seam-coverage facts diffed against
dpsvm_tpu/analysis/contracts/*.json. Pure AST, no jax import: the
threads check runs on a bare Python, which is why the routing below
happens BEFORE the budget module (and therefore jax) is imported.

Usage:
    python -m tools.tpulint --check           # CI / pre-merge gate
    python -m tools.tpulint --write-budgets   # after an INTENTIONAL
                                              # structural change;
                                              # commit the JSON diff
    python -m tools.tpulint --check --entries mesh_chunk,serve_bucket
    python -m tools.tpulint --threads --check # concurrency contracts
    python -m tools.tpulint --threads --write-contracts

Exit status: 0 iff every checked entrypoint PASSes its budget.

No TPU required — the facts are properties of the lowered programs,
which is the point: the paper's contract (one gather per sync, dense
GEMV kernel rows, no host round-trips) is checkable on every CI run.
"""

import sys
from pathlib import Path


def _threadlint_module():
    """The threadlint module, importable even without jax: the
    dpsvm_tpu package __init__ pulls jax, so fall back to loading the
    analyzer file directly (it is stdlib-only by design)."""
    try:
        from dpsvm_tpu.analysis import threadlint
        return threadlint
    except Exception:
        import importlib.util

        path = (Path(__file__).resolve().parent.parent
                / "dpsvm_tpu" / "analysis" / "threadlint.py")
        spec = importlib.util.spec_from_file_location(
            "dpsvm_threadlint", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if "--threads" in argv:
        # Route BEFORE any dpsvm_tpu/jax import — the concurrency
        # contracts are host-source facts and must stay checkable on
        # an interpreter with no accelerator stack at all.
        argv.remove("--threads")
        return _threadlint_module().run_threadlint(argv)
    # Backend forcing (CPU platform, the manifest's virtual device
    # count) lives in ONE place — budget._force_cpu_backend, which
    # run_lint applies before any jax backend initialization.
    from dpsvm_tpu.analysis.budget import run_lint

    return run_lint(argv)


if __name__ == "__main__":
    sys.exit(main())
