"""tpulint — static HLO/jaxpr contract linter (ISSUE 5).

Lowers the manifest of hot entrypoints (dpsvm_tpu/analysis/manifest.py)
at canonical shapes on the CPU backend, extracts structured facts
(collective ops + payload bytes, dispatch counts, host transfers,
dtype-promotion leaks, rank-3 kernel products, donation misses,
recompile hazards), and diffs them against the checked-in budgets in
dpsvm_tpu/analysis/budgets/*.json.

Usage:
    python -m tools.tpulint --check           # CI / pre-merge gate
    python -m tools.tpulint --write-budgets   # after an INTENTIONAL
                                              # structural change;
                                              # commit the JSON diff
    python -m tools.tpulint --check --entries mesh_chunk,serve_bucket

Exit status: 0 iff every checked entrypoint PASSes its budget.

No TPU required — the facts are properties of the lowered programs,
which is the point: the paper's contract (one gather per sync, dense
GEMV kernel rows, no host round-trips) is checkable on every CI run.
"""

import sys


def main(argv=None) -> int:
    # Backend forcing (CPU platform, the manifest's virtual device
    # count) lives in ONE place — budget._force_cpu_backend, which
    # run_lint applies before any jax backend initialization.
    from dpsvm_tpu.analysis.budget import run_lint

    return run_lint(argv)


if __name__ == "__main__":
    sys.exit(main())
