"""Per-stage cost breakdown of one block-engine round on the real TPU.

Times each stage of solver/block.py's round body in isolation by running
it repeatedly inside a jitted fori_loop (host-side timing of single ops is
meaningless through the tunnel — see utils/metrics.py). Stages:

  select   — select_block (masks + batched top_k over n)
  gather   — working-set row/scalar gathers (q HBM row DMAs)
  gram     — (q,d)x(d,q) Gram block + diag
  inner    — the Pallas subproblem solve (`limit` pair updates)
  fold     — kernel_rows (n,d)x(d,q) + f += coef @ k_rows
  scatter  — owned-slot alpha scatter (extrema ride the select stage)
  full     — the real run_chunk_block round for comparison

Run: `python tools/profile_round.py [--dataset mnist|covtype] [--q 512]`.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def timed(fn, *args, reps: int) -> float:
    """Seconds per repetition of fn, measured inside one dispatch."""
    import jax
    from jax import lax

    @jax.jit
    def loop(*a):
        def body(i, carry):
            return fn(*carry)
        return lax.fori_loop(0, reps, body, a)

    out = loop(*args)
    jax.block_until_ready(out)  # compile
    t0 = time.perf_counter()
    jax.block_until_ready(loop(*args))
    return (time.perf_counter() - t0) / reps


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--dataset", default="mnist",
                    choices=["mnist", "covtype"])
    ap.add_argument("--q", type=int, default=512)
    ap.add_argument("--reps", type=int, default=200)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from dpsvm_tpu.config import SVMConfig
    from dpsvm_tpu.ops.kernels import (KernelParams, kernel_diag,
                                       kernel_from_dots, kernel_rows,
                                       squared_norms)
    from dpsvm_tpu.ops.pallas_subproblem import solve_subproblem_pallas
    from dpsvm_tpu.solver.block import select_block

    if args.dataset == "mnist":
        from dpsvm_tpu.data.synth import make_mnist_like
        x, y = make_mnist_like(n=60_000, d=784, seed=7, noise=0.1)
        cfg = SVMConfig(c=10.0, gamma=0.125, epsilon=0.01)
    else:
        rng = np.random.default_rng(0)
        x = (rng.normal(size=(500_000, 54)) * 0.3).astype(np.float32)
        y = np.where(x[:, 0] + 0.2 * rng.standard_normal(len(x)) > 0,
                     1, -1).astype(np.int32)
        cfg = SVMConfig(c=2048.0, gamma=0.03125, epsilon=1e-3)

    q = args.q
    n, d = x.shape
    kp = KernelParams("rbf", cfg.resolve_gamma(d))
    xd = jnp.asarray(x, jnp.bfloat16)
    yd = jnp.asarray(y, jnp.float32)
    x_sq = jax.jit(squared_norms)(xd)
    k_diag = jax.jit(kernel_diag, static_argnames="params")(x_sq, params=kp)
    rng = np.random.default_rng(1)
    alpha = jnp.asarray(np.clip(rng.normal(1.0, 1.0, n), 0, cfg.c),
                        jnp.float32)
    f = jnp.asarray(rng.normal(0, 1, n), jnp.float32)
    print(f"dataset={args.dataset} n={n} d={d} q={q} reps={args.reps}")

    c = cfg.c_bounds()

    # --- select
    def s_select(f, alpha):
        w, ok, b_hi, b_lo = select_block(f, alpha, yd, c, q)
        return f + 1e-20 * w[0], alpha  # data-dependence, no real change

    t_sel = timed(s_select, f, alpha, reps=args.reps)

    w, ok, _, _ = jax.jit(lambda f, a: select_block(f, a, yd, c, q))(f, alpha)

    # --- gather
    def s_gather(f, alpha):
        qx = jnp.take(xd, w, axis=0)
        qsq = jnp.take(x_sq, w)
        aw = jnp.take(alpha, w)
        yw = jnp.take(yd, w)
        fw = jnp.take(f, w)
        kdw = jnp.take(k_diag, w)
        return f + 1e-20 * (jnp.sum(qx.astype(jnp.float32)) + qsq[0]
                            + aw[0] + yw[0] + fw[0] + kdw[0]), alpha

    t_gather = timed(s_gather, f, alpha, reps=args.reps)

    qx = jax.jit(lambda: jnp.take(xd, w, axis=0))()
    qsq = jnp.take(x_sq, w)
    aw = jnp.take(alpha, w)
    yw = jnp.take(yd, w)
    fw = jnp.take(f, w)
    kdw = jnp.take(k_diag, w)

    # --- gram
    def s_gram(f, alpha):
        dots = jnp.dot(qx, qx.T, preferred_element_type=jnp.float32)
        kb = kernel_from_dots(dots, qsq, qsq, kp)
        return f + 1e-20 * kb[0, 0], alpha

    t_gram = timed(s_gram, f, alpha, reps=args.reps)

    kb = jax.jit(lambda: kernel_from_dots(
        jnp.dot(qx, qx.T, preferred_element_type=jnp.float32),
        qsq, qsq, kp))()

    # --- inner (pallas subproblem, full budget)
    def s_inner(f, alpha):
        aw2, t = solve_subproblem_pallas(
            kb, aw, yw, fw, kdw, ok.astype(jnp.float32),
            jnp.int32(q), c, float(cfg.epsilon), float(cfg.tau))
        return f + 1e-20 * (aw2[0] + t), alpha

    t_inner = timed(s_inner, f, alpha, reps=max(20, args.reps // 4))

    # --- fold
    coef = jnp.asarray(rng.normal(0, 0.1, q), jnp.float32)

    def s_fold(f, alpha):
        k_rows = kernel_rows(xd, x_sq, qx, qsq, kp)
        return f + coef @ k_rows, alpha

    t_fold = timed(s_fold, f, alpha, reps=args.reps)

    # --- scatter (the round's extrema now ride the selection pass)
    def s_scatter(f, alpha):
        safe_w = jnp.where(ok, w, jnp.int32(n))
        alpha = alpha.at[safe_w].set(jnp.where(ok, aw, 0.0), mode="drop")
        return f + 1e-20 * alpha[0], alpha

    t_scatter = timed(s_scatter, f, alpha, reps=args.reps)

    # --- full round for comparison
    from dpsvm_tpu.solver.block import BlockState, run_chunk_block

    st = BlockState(alpha=alpha, f=f, b_hi=jnp.float32(-1e9),
                    b_lo=jnp.float32(1e9), pairs=jnp.int32(0),
                    rounds=jnp.int32(0))
    runner = lambda st: run_chunk_block(
        xd, yd, x_sq, k_diag, st, jnp.int32(10**9), kp, c,
        float(cfg.epsilon), float(cfg.tau), q, q, args.reps,
        inner_impl="pallas")
    out = runner(st)  # compile + warm
    jax.block_until_ready(out)
    # Time a SECOND execution from the same fresh state: continuing from
    # the warmed-up state instead would run degenerate near-converged
    # rounds (or zero rounds once the gap closes) and poison the average.
    t0 = time.perf_counter()
    out2 = runner(st)
    jax.block_until_ready(out2)
    t_full = (time.perf_counter() - t0) / max(int(out2.rounds), 1)
    print(f"  (full-round chunk executed {int(out2.rounds)} rounds, "
          f"{int(out2.pairs)} pairs)")

    total = t_sel + t_gather + t_gram + t_inner + t_fold + t_scatter
    for name, t in [("select", t_sel), ("gather", t_gather),
                    ("gram", t_gram), ("inner(pallas)", t_inner),
                    ("fold", t_fold), ("scatter", t_scatter),
                    ("SUM", total), ("FULL ROUND", t_full)]:
        print(f"  {name:15s} {1e3 * t:8.3f} ms")
    return 0


if __name__ == "__main__":
    sys.exit(main())
