"""Per-stage cost breakdown of one block-engine round on the real TPU.

Times each stage of solver/block.py's round body in isolation by running
it repeatedly inside a jitted fori_loop (host-side timing of single ops is
meaningless through the tunnel — see utils/metrics.py). Stages:

  select   — select_block (masks + batched top_k over n)
  gather   — working-set row/scalar gathers (q HBM row DMAs)
  gram     — (q,d)x(d,q) Gram block + diag
  inner    — the Pallas subproblem solve (`limit` pair updates)
  fold     — kernel_rows (n,d)x(d,q) + f += coef @ k_rows
  scatter  — owned-slot alpha scatter (extrema ride the select stage)
  full     — the real run_chunk_block round for comparison

Run: `python tools/profile_round.py [--dataset mnist|covtype] [--q 512]`.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# The measurement core is SHARED with the autotune registry probes
# (ISSUE 14 satellite: tool ablations and autotune probes are the same
# measurement): salted off-clock perturbation, fori_loop-differenced
# stage timing, and the whole-chunk differenced runner.
from dpsvm_tpu.autotune.probe import (differenced_rounds, salted,  # noqa: E402
                                      timed_loop as timed)


def ablate(xd, yd, x_sq, k_diag, kp, cfg, q: int, reps: int,
           fused: bool = False, valid=None, budgets=None,
           pipelined: bool = False, fusedround: bool = False):
    """Stage attribution from WHOLE-CHUNK ablation — the only timing
    method the tunnel cannot distort (one dispatch per probe, big-state
    output, salted fresh start each time). Runs `reps` rounds at
    inner budgets {1, q//4, q, 2q} and derives:

      fixed ms/round   = chunk time at inner=1 (selection + gathers +
                         Gram + fold + scatter + ONE pair)
      marginal us/pair = slope of chunk time vs executed pairs across
                         budgets (the serial subproblem chain's per-pair
                         cost, free of every per-round fixed term)

    Returns (rows, fixed_ms, marginal_us)."""
    import jax
    import jax.numpy as jnp

    from dpsvm_tpu.solver.block import (BlockState, run_chunk_block,
                                        run_chunk_block_fused,
                                        run_chunk_block_fusedround,
                                        run_chunk_block_pipelined)
    from dpsvm_tpu.solver.smo import _BUDGET_EPS

    base = BlockState(alpha=jnp.zeros_like(yd),
                      f=(-yd).astype(jnp.float32),
                      b_hi=jnp.float32(-1e9), b_lo=jnp.float32(1e9),
                      pairs=jnp.int32(0), rounds=jnp.int32(0))
    rows = []

    for bi, inner in enumerate(budgets or (1, max(2, q // 4), q, 2 * q)):
        # _BUDGET_EPS keeps the stopping test open so EVERY probe runs
        # its exact round budget with its full inner budget — from the
        # zero start the mnist shape otherwise converges mid-probe,
        # making rounds/pairs differ across budgets and the slope
        # meaningless. Post-optimum rounds execute the identical
        # instruction stream, so the cost model is unaffected.
        # Off-TPU the Pallas kernels have no compiled lowering: fall back
        # to the XLA subproblem + interpret-mode fold kernels so the
        # probes still RUN (the numbers then measure the CPU platform —
        # a smoke check, not the TPU claim).
        on_tpu = jax.default_backend() == "tpu"
        impl = "pallas" if on_tpu else "xla"
        if fusedround:
            # The one-HBM-pass round (ISSUE 12): same padding contract
            # as the fused engine; the --fused-round A/B differences
            # this against the stock fused ablation.
            run = lambda st, n, inner=inner: run_chunk_block_fusedround(
                xd, yd, x_sq, k_diag, valid, st, jnp.int32(10 ** 9), kp,
                cfg.c_bounds(), _BUDGET_EPS, float(cfg.tau), q, inner,
                n, inner_impl=impl, interpret=not on_tpu)
        elif fused:
            run = lambda st, n, inner=inner: run_chunk_block_fused(
                xd, yd, x_sq, k_diag, valid, st, jnp.int32(10 ** 9), kp,
                cfg.c_bounds(), _BUDGET_EPS, float(cfg.tau), q, inner,
                n, inner_impl=impl, interpret=not on_tpu)
        elif pipelined:
            # The pipelined A/B probe (ISSUE 2 tentpole): same
            # whole-chunk ablation, run_chunk_block_pipelined body.
            # pallas_select rides the fused padding contract when the
            # caller padded (valid is not None); TPU only — in interpret
            # mode the per-round kernel would dominate everything.
            run = lambda st, n, inner=inner: run_chunk_block_pipelined(
                xd, yd, x_sq, k_diag, valid, st, jnp.int32(10 ** 9), kp,
                cfg.c_bounds(), _BUDGET_EPS, float(cfg.tau), q, inner,
                n, inner_impl=impl, interpret=not on_tpu,
                pallas_select=valid is not None and on_tpu)
        else:
            run = lambda st, n, inner=inner: run_chunk_block(
                xd, yd, x_sq, k_diag, None, st, jnp.int32(10 ** 9), kp,
                cfg.c_bounds(), _BUDGET_EPS, float(cfg.tau), q, inner,
                n, inner_impl=impl)
        # The shared differenced whole-chunk runner (autotune/probe.py):
        # warm + best-of-3 salted starts per chunk length, differenced
        # so the tunnel's fixed per-dispatch latency (~60-80 ms)
        # cancels instead of reading as +F/reps ms on every round.
        t, rounds, pairs = differenced_rounds(
            lambda rpc, run=run: (lambda st: run(st, rpc)),
            base, reps, salt_base=1000 * (bi + 1))
        rows.append((inner, rounds, pairs, 1e3 * t / max(rounds, 1),
                     1e6 * t / max(pairs, 1), t))
        print(f"  inner={inner:5d}: {rounds} rounds, {pairs} pairs, "
              f"{1e3 * t / max(rounds, 1):7.3f} ms/round, "
              f"{1e6 * t / max(pairs, 1):7.2f} us/pair  "
              f"(differenced {reps}/{2 * reps}-round chunks)")
    # Report LOCAL marginals between consecutive budgets (a single global
    # slope hides tunnel drift between probes; consecutive pairs taken
    # minutes apart still carry +-5-15% drift — treat each as an
    # independent estimate and read the spread as the error bar).
    for (i0, _, p0, _, _, t0), (i1, _, p1, _, _, t1) in zip(rows, rows[1:]):
        if p1 > p0:
            print(f"  marginal {i0}->{i1}: "
                  f"{1e6 * (t1 - t0) / (p1 - p0):6.2f} us/pair")
    fixed_ms = rows[0][3]
    marg = 1e6 * (rows[-1][5] - rows[0][5]) / max(rows[-1][2] - rows[0][2], 1)
    return rows, fixed_ms, marg


def ablate_shardlocal(x, y, cfg, q: int, reps: int, sync_rounds: int,
                      dtype: str):
    """Shard-local vs global mesh-runner whole-chunk A/B (ISSUE 4 —
    the measurement solver/block.py shardlocal_pays is waiting on).

    Builds a data mesh over every visible device and runs `reps`
    wall-clock rounds of each engine from a salted synthetic start at
    the full inner budget, differenced over two chunk lengths exactly
    like ablate(). Reports ms per wall-round and us per EXECUTED pair —
    the decisive comparison: the shard-local engine runs P concurrent
    chains, so at equal wall-round cost its pairs/s should approach
    P x the global runner's (minus the sync fold and any chain
    imbalance). On a 1-device harness the probe still runs (P=1
    measures pure sync overhead — the expected-loss regime the auto
    gate must also know about)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dpsvm_tpu.ops.kernels import (KernelParams, kernel_diag,
                                       squared_norms)
    from dpsvm_tpu.parallel.dist_block import (
        make_block_chunk_runner, make_block_shardlocal_chunk_runner)
    from dpsvm_tpu.parallel.mesh import DATA_AXIS, make_data_mesh, pad_rows
    from dpsvm_tpu.solver.block import BlockState
    from dpsvm_tpu.solver.smo import _BUDGET_EPS

    kp = KernelParams("rbf", cfg.resolve_gamma(x.shape[1]))
    mesh = make_data_mesh()
    p_dev = int(mesh.devices.size)
    on_tpu = jax.default_backend() == "tpu"
    impl = "pallas" if on_tpu else "xla"
    n, d = x.shape
    n_pad = pad_rows(n, p_dev)
    x_p = np.zeros((n_pad, d), np.float32)
    x_p[:n] = x
    y_p = np.ones((n_pad,), np.float32)
    y_p[:n] = y
    valid = np.zeros((n_pad,), bool)
    valid[:n] = True
    shard = NamedSharding(mesh, P(DATA_AXIS))
    rep = NamedSharding(mesh, P())
    xd = jax.device_put(jnp.asarray(
        x_p, jnp.bfloat16 if dtype == "bfloat16" else jnp.float32), shard)
    yd = jax.device_put(jnp.asarray(y_p), shard)
    x_sq = jax.jit(squared_norms, out_shardings=shard)(xd)
    k_diag = jax.jit(kernel_diag, static_argnames="params",
                     out_shardings=shard)(x_sq, params=kp)
    vd = jax.device_put(jnp.asarray(valid), shard)
    inner = 2 * q
    base = BlockState(
        alpha=jax.device_put(jnp.zeros((n_pad,), jnp.float32), shard),
        f=jax.device_put(jnp.asarray(-y_p, jnp.float32), shard),
        b_hi=jax.device_put(jnp.float32(-1e9), rep),
        b_lo=jax.device_put(jnp.float32(1e9), rep),
        pairs=jax.device_put(jnp.int32(0), rep),
        rounds=jax.device_put(jnp.int32(0), rep))

    # rounds_per_chunk is a traced constant baked at build time: build
    # one runner per chunk length so the differencing has two programs
    # with identical per-round bodies.
    def make(kind, rpc):
        if kind == "global":
            return make_block_chunk_runner(
                mesh, kp, cfg.c_bounds(), _BUDGET_EPS, float(cfg.tau),
                q, inner, rpc, impl)
        rpc = -(-rpc // sync_rounds) * sync_rounds
        return make_block_shardlocal_chunk_runner(
            mesh, kp, cfg.c_bounds(), _BUDGET_EPS, float(cfg.tau),
            q, inner, rpc, sync_rounds, impl, interpret=not on_tpu)

    print(f"  shard-local A/B: P={p_dev} devices, q={q}, inner={inner}, "
          f"sync_rounds={sync_rounds}, reps={reps}")
    results = {}
    for ki, kind in enumerate(("global", "shardlocal")):
        # Shared differenced whole-chunk runner (autotune/probe.py).
        # Salt bases are DISJOINT from ablate_ring's 7000*(vi+1)
        # family: --ring --shardlocal in one process share the same
        # global chunk runner + operands, and a colliding salt would
        # re-dispatch content-identical states the result cache can
        # serve without executing (the ~0 ms trap probe.py documents).
        t, rounds, pairs = differenced_rounds(
            lambda rpc, kind=kind: (
                lambda st, r=make(kind, rpc): r(
                    xd, yd, x_sq, k_diag, vd, st, jnp.int32(10 ** 9))),
            base, reps, salt_base=50000 * (ki + 1))
        results[kind] = (t, rounds, pairs)
        print(f"  {kind:10s}: {rounds} rounds, {pairs} pairs, "
              f"{1e3 * t / max(rounds, 1):7.3f} ms/round, "
              f"{1e6 * t / max(pairs, 1):7.2f} us/pair "
              f"({pairs / max(t, 1e-9):,.0f} pairs/s)")
    tg, _, pg = results["global"]
    ts, _, ps = results["shardlocal"]
    if tg > 0 and ts > 0:
        print(f"  => shard-local pairs/s = "
              f"{(ps / ts) / max(pg / tg, 1e-9):.2f}x the global "
              f"runner's (ideal ~{p_dev}x minus sync overhead; flip "
              f"solver/block.py shardlocal_pays from THIS number, "
              f"measured on a real pod)")
    return 0


def ablate_ring(x, y, cfg, q: int, reps: int, sync_rounds: int,
                dtype: str, obs_cfg=None):
    """Ring-vs-all_gather whole-chunk A/B (ISSUE 11 — the measurement
    solver/block.py ring_pays is waiting on): the global and shard-local
    mesh runners each run with the collective exchange and with the
    Pallas DMA ring (ops/ring.py), same salted starts, differenced over
    two chunk lengths exactly like ablate_shardlocal. Trajectories are
    bit-identical by construction (tests/test_ring.py), so the pairs
    executed match and ms/round is the decisive number. On a CPU
    harness the ring runs in interpret mode — the numbers are a
    STRUCTURE check only (the interpreter emulates DMAs with gathers);
    flip ring_pays only from a real-device run of this probe."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dpsvm_tpu.ops.kernels import (KernelParams, kernel_diag,
                                       squared_norms)
    from dpsvm_tpu.parallel.dist_block import (
        make_block_chunk_runner, make_block_shardlocal_chunk_runner)
    from dpsvm_tpu.parallel.mesh import DATA_AXIS, make_data_mesh, pad_rows
    from dpsvm_tpu.solver.block import BlockState
    from dpsvm_tpu.solver.smo import _BUDGET_EPS

    kp = KernelParams("rbf", cfg.resolve_gamma(x.shape[1]))
    mesh = make_data_mesh()
    p_dev = int(mesh.devices.size)
    if p_dev < 2:
        print("  ring A/B needs >= 2 devices (a one-device ring has no "
              "hops); nothing to measure")
        return 0
    on_tpu = jax.default_backend() == "tpu"
    impl = "pallas" if on_tpu else "xla"
    n, d = x.shape
    n_pad = pad_rows(n, p_dev)
    x_p = np.zeros((n_pad, d), np.float32)
    x_p[:n] = x
    y_p = np.ones((n_pad,), np.float32)
    y_p[:n] = y
    valid = np.zeros((n_pad,), bool)
    valid[:n] = True
    shard = NamedSharding(mesh, P(DATA_AXIS))
    rep = NamedSharding(mesh, P())
    xd = jax.device_put(jnp.asarray(
        x_p, jnp.bfloat16 if dtype == "bfloat16" else jnp.float32), shard)
    yd = jax.device_put(jnp.asarray(y_p), shard)
    x_sq = jax.jit(squared_norms, out_shardings=shard)(xd)
    k_diag = jax.jit(kernel_diag, static_argnames="params",
                     out_shardings=shard)(x_sq, params=kp)
    vd = jax.device_put(jnp.asarray(valid), shard)
    inner = 2 * q
    base = BlockState(
        alpha=jax.device_put(jnp.zeros((n_pad,), jnp.float32), shard),
        f=jax.device_put(jnp.asarray(-y_p, jnp.float32), shard),
        b_hi=jax.device_put(jnp.float32(-1e9), rep),
        b_lo=jax.device_put(jnp.float32(1e9), rep),
        pairs=jax.device_put(jnp.int32(0), rep),
        rounds=jax.device_put(jnp.int32(0), rep))

    def make(kind, ring, rpc):
        if kind == "global":
            return make_block_chunk_runner(
                mesh, kp, cfg.c_bounds(), _BUDGET_EPS, float(cfg.tau),
                q, inner, rpc, impl, interpret=not on_tpu,
                ring_exchange=ring)
        rpc = -(-rpc // sync_rounds) * sync_rounds
        return make_block_shardlocal_chunk_runner(
            mesh, kp, cfg.c_bounds(), _BUDGET_EPS, float(cfg.tau),
            q, inner, rpc, sync_rounds, impl, interpret=not on_tpu,
            ring_exchange=ring)

    print(f"  ring A/B: P={p_dev} devices, q={q}, inner={inner}, "
          f"sync_rounds={sync_rounds}, reps={reps}"
          + ("" if on_tpu else "  [interpret mode — structure only]"))
    rows = []
    for vi, (kind, ring) in enumerate(
            (k, r) for k in ("global", "shardlocal")
            for r in (False, True)):
        # Shared differenced whole-chunk runner (autotune/probe.py).
        t, rounds, pairs = differenced_rounds(
            lambda rpc, kind=kind, ring=ring: (
                lambda st, r=make(kind, ring, rpc): r(
                    xd, yd, x_sq, k_diag, vd, st, jnp.int32(10 ** 9))),
            base, reps, salt_base=7000 * (vi + 1))
        label = f"{kind}:{'ring' if ring else 'gather'}"
        rows.append((label, t, rounds, pairs))
        print(f"  {label:18s}: {rounds} rounds, {pairs} pairs, "
              f"{1e3 * t / max(rounds, 1):7.3f} ms/round "
              f"({pairs / max(t, 1e-9):,.0f} pairs/s)")
    by = {lbl: (t, r, p) for lbl, t, r, p in rows}
    for kind in ("global", "shardlocal"):
        tg = by[f"{kind}:gather"][0]
        tr = by[f"{kind}:ring"][0]
        if tg > 0 and tr > 0:
            print(f"  => {kind}: ring wall-clock = {tr / tg:.2f}x the "
                  f"gather path's (flip solver/block.py ring_pays from "
                  f"THIS number, measured on a real pod)")
    if obs_cfg is not None:
        from dpsvm_tpu.obs import obs_enabled
        from dpsvm_tpu.obs.runlog import RunLog

        if obs_enabled(obs_cfg):
            with RunLog.open("profile_round", obs_config=obs_cfg,
                             meta={"probe": "ring", "q": q,
                                   "sync_rounds": sync_rounds,
                                   "n_devices": p_dev, "dtype": dtype,
                                   "interpret": not on_tpu}) as rl:
                for label, t, rounds, pairs in rows:
                    rl.record("ablation", variant=label,
                              rounds=int(rounds), pairs=int(pairs),
                              ms_per_round=round(
                                  1e3 * t / max(rounds, 1), 4),
                              device_seconds=round(t, 6))
                rl.finish()
    return 0


def ablate_bf16_gram(x, y, cfg, q: int, reps: int, obs_cfg=None):
    """bf16-vs-f32 Gram-path whole-chunk A/B (ISSUE 11): the single-chip
    block chunk runner timed with X stored float32 vs bfloat16 — the
    exact storage flip config.bf16_gram makes when the perturbation
    bound accepts — plus the gate's own verdict on this data. The fold
    and Gram passes read X, so the bf16 win is bounded by their share
    of the round (PROFILE.md roofline); record the measured ratio next
    to the gate decision."""
    import jax
    import jax.numpy as jnp

    from dpsvm_tpu.ops.kernels import (KernelParams, kernel_diag,
                                       resolve_bf16_gram, squared_norms)
    # The UNDONATED runner: the probe legitimately re-dispatches a
    # warmed state (the _jit_runner note in parallel/dist_block.py).
    from dpsvm_tpu.solver.block import BlockState, run_chunk_block
    from dpsvm_tpu.solver.smo import _BUDGET_EPS

    n, d = x.shape
    gamma = cfg.resolve_gamma(d)
    kp = KernelParams("rbf", gamma)
    active, risk, entry = resolve_bf16_gram(x, cfg.replace(bf16_gram=True),
                                            gamma)
    print(f"  bf16-gram gate on this data: active={active} "
          f"risk={risk:.4g} (threshold {entry['threshold']})")
    inner = 2 * q
    rows = []
    for dt_name, dt in (("float32", jnp.float32),
                        ("bfloat16", jnp.bfloat16)):
        xd = jnp.asarray(x, dt)
        x_sq = jax.jit(squared_norms)(xd)
        kd = jax.jit(kernel_diag, static_argnames="params")(x_sq,
                                                            params=kp)
        yd = jnp.asarray(y, jnp.float32)
        vd = jnp.ones((n,), bool)
        base = BlockState(
            alpha=jnp.zeros((n,), jnp.float32), f=-yd,
            b_hi=jnp.float32(-1e9), b_lo=jnp.float32(1e9),
            pairs=jnp.int32(0), rounds=jnp.int32(0))
        # Shared differenced whole-chunk runner (autotune/probe.py).
        def make_run(rpc, xd=xd, x_sq=x_sq, kd=kd, yd=yd, vd=vd):
            kw = dict(kp=kp, c=cfg.c_bounds(), eps=_BUDGET_EPS,
                      tau=float(cfg.tau), q=q, inner_iters=inner,
                      rounds_per_chunk=rpc, inner_impl="xla")
            return lambda st: run_chunk_block(
                xd, yd, x_sq, kd, vd, st, jnp.int32(10 ** 9), **kw)

        t, rounds, pairs = differenced_rounds(
            make_run, base, reps,
            salt_base=11000 * (1 if dt_name == "float32" else 2))
        rows.append((dt_name, t, rounds, pairs))
        print(f"  x dtype {dt_name:9s}: {rounds} rounds, {pairs} pairs, "
              f"{1e3 * t / max(rounds, 1):7.3f} ms/round "
              f"({pairs / max(t, 1e-9):,.0f} pairs/s)")
    tf, tb = rows[0][1], rows[1][1]
    if tf > 0 and tb > 0:
        print(f"  => bf16 Gram wall-clock = {tb / tf:.2f}x float32's "
              f"(HBM-bound rounds should approach 0.5x on device; "
              f"gate verdict above says whether THIS problem may use it)")
    if obs_cfg is not None:
        from dpsvm_tpu.obs import obs_enabled
        from dpsvm_tpu.obs.runlog import RunLog

        if obs_enabled(obs_cfg):
            with RunLog.open("profile_round", obs_config=obs_cfg,
                             meta={"probe": "bf16_gram", "q": q,
                                   "gate_active": bool(active),
                                   "gate_risk": round(risk, 6)}) as rl:
                for dt_name, t, rounds, pairs in rows:
                    rl.record("ablation", variant=dt_name,
                              rounds=int(rounds), pairs=int(pairs),
                              ms_per_round=round(
                                  1e3 * t / max(rounds, 1), 4),
                              device_seconds=round(t, 6))
                rl.finish()
    return 0


def ablate_ooc_shrink(n: int, d: int, budget: int = 20_000,
                      tile_rows: int = 512, m: int = 0) -> int:
    """End-to-end A/B of the shrunken ooc tile stream (ISSUE 19 — the
    measurement the solver/block.py ooc_shrink_pays auto gate is
    waiting on): one budget-mode ooc solve with shrinking forced ON vs
    the identical solve with the full stream, same covtype-shaped data
    and pair budget. Reports wall, pairs/s, tiles streamed/skipped,
    stream bytes, and the late-phase (in-cycle) byte cut. On the CPU
    harness the H2D put is a memcpy, so the BYTE columns are the
    decisive ones — flip the gate from a device run."""
    from dpsvm_tpu.config import SVMConfig
    from dpsvm_tpu.data import make_covtype_like
    from dpsvm_tpu.solver.smo import solve

    x, y = make_covtype_like(n, d, seed=0)
    base = SVMConfig(c=32.0, gamma=0.03125, epsilon=1e-3,
                     engine="block", working_set_size=256,
                     budget_mode=True, max_iter=budget, ooc=True,
                     ooc_tile_rows=tile_rows)
    arms = [("shrink", base.replace(
        ooc_shrink=True, **({"active_set_size": m} if m else {}))),
        ("full  ", base)]
    print(f"ooc shrink A/B: covtype-shaped n={n} d={d} "
          f"tile_rows={tile_rows} budget={budget}"
          + (f" m={m}" if m else " (auto m)"))
    rows = {}
    for label, cfg in arms:
        solve(x, y, cfg.replace(max_iter=64))  # warm the executors
        res = min((solve(x, y, cfg) for _ in range(2)),
                  key=lambda r: r.train_seconds)
        st = res.stats
        pps = res.iterations / max(res.train_seconds, 1e-9)
        rows[label.strip()] = st
        in_cyc = st.get("shrink_tiles_in_cycle", 0)
        skip = st.get("tiles_skipped", 0)
        cut = ((in_cyc + skip) / in_cyc) if in_cyc else float("nan")
        print(f"  {label}: {res.iterations} pairs "
              f"{res.train_seconds:.3f}s ({pps:.0f}/s) "
              f"tiles={st['tiles_streamed']} "
              f"bytes={st['tile_bytes_h2d']}"
              + (f" skipped={skip} cycles={st.get('shrink_cycles')} "
                 f"recon={st.get('shrink_reconstructions')} "
                 f"late-cut={cut:.2f}x "
                 f"demoted={st.get('shrink_demoted')}"
                 if st.get("ooc_shrink") else ""))
    s, f = rows["shrink"], rows["full"]
    if f["tile_bytes_h2d"]:
        print(f"  => stream bytes {s['tile_bytes_h2d']} vs "
              f"{f['tile_bytes_h2d']} "
              f"({f['tile_bytes_h2d'] / max(s['tile_bytes_h2d'], 1):.2f}x"
              f" cut overall; flip solver/block.py ooc_shrink_pays "
              f"from THIS number, measured on a real device)")
    return 0


# v5e per-chip ceilings (Google's published spec): the MXU runs bf16
# (and default-precision f32, which lowers to one bf16 pass) matmuls at
# 197 TFLOP/s; 'highest' f32 is ~6 bf16 passes. HBM streams 819 GB/s.
_V5E_MXU_BF16 = 197e12
_V5E_HBM_BPS = 819e9


def roofline(n: int, d: int, q: int, dtype: str, fixed_ms: float = None,
             inner: int = 2048, pair_us: float = 0.51):
    """Per-stage FLOP/byte counts of one block round vs the v5e ceilings
    (VERDICT round-5 item 4: judge 'is it fast' against the hardware,
    not a 2013 GPU). Analytic counts from the round's algebra; when a
    measured fixed round cost is given (--fixed-ms, from the whole-chunk
    ablation or PROFILE.md's pinned tables), also prints achieved
    TFLOP/s / GB/s and MFU. Emits a markdown table ready for PROFILE.md.
    """
    bx = 2 if dtype == "bfloat16" else 4
    stages = [
        # (stage, FLOPs, HBM bytes) — matmul FLOPs dominate; elementwise
        # kernel evals counted at their op count, reductions at one pass.
        ("fold: K(W,:) dots (q,d)x(d,n)", 2.0 * n * d * q, n * d * bx),
        ("fold: kernel eval + coef contraction", 6.0 * n * q, 4.0 * n),
        ("Gram block (q,d)x(d,q)", 2.0 * q * q * d, q * d * bx),
        ("selection masks + top-k", 10.0 * n, 3 * 4.0 * n),
        ("gathers + scatter", 0.0, (q * d * bx) + 2 * 4.0 * q),
    ]
    tot_f = sum(s[1] for s in stages)
    tot_b = sum(s[2] for s in stages)
    print(f"\n## Roofline — one block round, n={n} d={d} q={q} "
          f"dtype={dtype} (v5e: {_V5E_MXU_BF16 / 1e12:.0f} TFLOP/s bf16 "
          f"MXU, {_V5E_HBM_BPS / 1e9:.0f} GB/s HBM)\n")
    print("| stage | GFLOP | MB read+written | min ms (MXU) | min ms "
          "(HBM) |")
    print("|---|---|---|---|---|")
    for name, fl, by in stages:
        print(f"| {name} | {fl / 1e9:.2f} | {by / 1e6:.1f} | "
              f"{1e3 * fl / _V5E_MXU_BF16:.3f} | "
              f"{1e3 * by / _V5E_HBM_BPS:.3f} |")
    t_mxu = 1e3 * tot_f / _V5E_MXU_BF16
    t_hbm = 1e3 * tot_b / _V5E_HBM_BPS
    print(f"| **total** | {tot_f / 1e9:.2f} | {tot_b / 1e6:.1f} | "
          f"{t_mxu:.3f} | {t_hbm:.3f} |")
    bound = "compute (MXU)" if t_mxu > t_hbm else "bandwidth (HBM)"
    print(f"\nRoofline bound for the FIXED round cost: {bound} at "
          f"{max(t_mxu, t_hbm):.3f} ms/round minimum.")
    if fixed_ms:
        mfu = tot_f / (fixed_ms * 1e-3) / _V5E_MXU_BF16
        bw = tot_b / (fixed_ms * 1e-3) / _V5E_HBM_BPS
        print(f"Measured fixed round cost {fixed_ms:.3f} ms => "
              f"{tot_f / (fixed_ms * 1e-3) / 1e12:.1f} TFLOP/s "
              f"({100 * mfu:.1f}% MFU), "
              f"{tot_b / (fixed_ms * 1e-3) / 1e9:.0f} GB/s "
              f"({100 * bw:.1f}% of HBM) — the gap to the larger bound "
              f"is the serial stage-sequence latency PROFILE.md reading "
              f"4 identifies.")
        # The full round at the operating point: fixed + serial chain.
        t_round = fixed_ms + inner * pair_us * 1e-3
        mfu_op = tot_f / (t_round * 1e-3) / _V5E_MXU_BF16
        print(f"At the inner={inner} operating point "
              f"({pair_us:.2f} us/pair chain): {t_round:.3f} ms/round "
              f"=> {100 * mfu_op:.1f}% MFU; a FULLY overlapped pipelined "
              f"round (fixed hidden behind the chain) would run "
              f"max({fixed_ms:.3f}, {inner * pair_us * 1e-3:.3f}) ms "
              f"=> {100 * tot_f / (max(fixed_ms, inner * pair_us * 1e-3) * 1e-3) / _V5E_MXU_BF16:.1f}% MFU.")
    return 0


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--dataset", default="mnist",
                    choices=["mnist", "covtype"])
    ap.add_argument("--q", type=int, default=512)
    ap.add_argument("--reps", type=int, default=200)
    ap.add_argument("--n", type=int, default=None,
                    help="row-count override (docs/SCALING.md uses the "
                         "fixed-cost slope between two n's at equal d/q)")
    ap.add_argument("--fused", action="store_true",
                    help="ablate run_chunk_block_fused (fold+select as "
                         "one Pallas pass; rows padded to 1024)")
    ap.add_argument("--fused-round", action="store_true",
                    help="A/B the one-HBM-pass fused round "
                         "(ops/pallas_round.py, config.fused_round) "
                         "against the stock fused engine: both whole-"
                         "chunk ablations back to back over the same "
                         "inner budgets, rows mirrored into the obs "
                         "runlog with --obs (ISSUE 12; the probe the "
                         "fused_round_pays auto gate is waiting on — "
                         "interpret-mode structure check on CPU)")
    ap.add_argument("--pipeline", action="store_true",
                    help="ablate run_chunk_block_pipelined (next round's "
                         "selection/gather/Gram issued from the pre-fold "
                         "carry; rows padded to 1024 so the prefetch "
                         "rides the Pallas candidate kernel) — the "
                         "pipelined-vs-plain fixed-cost A/B of ISSUE 2")
    ap.add_argument("--shardlocal", action="store_true",
                    help="A/B the shard-local mesh runner against the "
                         "global-working-set mesh runner over every "
                         "visible device (ISSUE 4: P concurrent "
                         "subproblem chains per sync; the probe the "
                         "shardlocal_pays auto gate is waiting on)")
    ap.add_argument("--sync-rounds", type=int, default=4,
                    help="--shardlocal/--ring: local rounds between "
                         "syncs (default 4)")
    ap.add_argument("--ring", action="store_true",
                    help="A/B the Pallas DMA-ring candidate exchange "
                         "against the all_gather path on the global AND "
                         "shard-local mesh runners over every visible "
                         "device (ISSUE 11; the probe the ring_pays "
                         "auto gate is waiting on — interpret-mode "
                         "structure check on CPU)")
    ap.add_argument("--ooc-shrink", action="store_true",
                    help="A/B the shrunken ooc tile stream against the "
                         "full stream at the same pair budget on "
                         "covtype-shaped data (ISSUE 19; the probe the "
                         "ooc_shrink_pays auto gate is waiting on — "
                         "tiles/bytes skipped and the late-phase cut)")
    ap.add_argument("--bf16-gram", action="store_true",
                    help="A/B the single-chip block chunk with X stored "
                         "float32 vs bfloat16 (the config.bf16_gram "
                         "storage flip) and print the perturbation "
                         "gate's verdict for this data (ISSUE 11)")
    ap.add_argument("--roofline", action="store_true",
                    help="print the per-stage FLOPs/bytes roofline table "
                         "vs the v5e MXU/HBM ceilings and exit (no "
                         "device work; pass --fixed-ms for achieved "
                         "MFU)")
    ap.add_argument("--fixed-ms", type=float, default=None,
                    help="measured fixed round cost for --roofline's "
                         "MFU lines (from the whole-chunk ablation)")
    ap.add_argument("--ablate-only", action="store_true",
                    help="skip the indicative isolated-stage probes and "
                         "run only the authoritative whole-chunk ablation")
    ap.add_argument("--budgets", default=None,
                    help="comma-separated inner budgets for the ablation "
                         "(default: 1,q/4,q,2q)")
    ap.add_argument("--dtype", default="bfloat16",
                    choices=["bfloat16", "float32"],
                    help="X storage dtype for the probes (bench_covtype "
                         "pins float32 for quality; the fold reads X so "
                         "its cost depends on this)")
    ap.add_argument("--obs", action="store_true",
                    help="write the ablation rows to a profile_round "
                         "run log (dpsvm_tpu/obs/runlog — the same "
                         "schema-versioned JSONL the solver and bench "
                         "emit; DPSVM_OBS=1 equivalent)")
    ap.add_argument("--obs-dir", default=None,
                    help="run-log directory (default obs_runs; env "
                         "DPSVM_OBS_DIR)")
    args = ap.parse_args()

    def obs_log_rows(label, rows, fixed_ms, marg_us):
        """Mirror an ablation table into the shared run-log substrate
        (one 'ablation' record per inner budget) when obs is enabled —
        the ROADMAP-5 autotuner's future input format."""
        from dpsvm_tpu.config import ObsConfig
        from dpsvm_tpu.obs import obs_enabled
        from dpsvm_tpu.obs.runlog import RunLog

        ocfg = ObsConfig(enabled=args.obs, runlog_dir=args.obs_dir)
        if not obs_enabled(ocfg):
            return
        with RunLog.open("profile_round", obs_config=ocfg,
                         meta={"probe": label, "dataset": args.dataset,
                               "q": args.q, "dtype": args.dtype}) as rl:
            for inner, rounds, pairs, ms_round, us_pair, t in rows:
                rl.record("ablation", inner=int(inner),
                          rounds=int(rounds), pairs=int(pairs),
                          ms_per_round=round(ms_round, 4),
                          us_per_pair=round(us_pair, 3),
                          device_seconds=round(t, 6))
            rl.finish(fixed_ms=round(fixed_ms, 4),
                      marginal_us_per_pair=round(marg_us, 3))

    if args.ooc_shrink:
        return ablate_ooc_shrink(args.n or 16384, 54)

    import jax
    import jax.numpy as jnp

    from dpsvm_tpu.config import SVMConfig
    from dpsvm_tpu.ops.kernels import (KernelParams, kernel_diag,
                                       kernel_from_dots, kernel_rows,
                                       squared_norms)
    from dpsvm_tpu.ops.pallas_subproblem import solve_subproblem_pallas
    from dpsvm_tpu.solver.block import select_block

    if args.dataset == "mnist":
        from dpsvm_tpu.data.synth import make_mnist_like
        x, y = make_mnist_like(n=args.n or 60_000, d=784, seed=7, noise=0.1)
        cfg = SVMConfig(c=10.0, gamma=0.125, epsilon=0.01)
    else:
        rng = np.random.default_rng(0)
        nn = args.n or 500_000
        x = (rng.normal(size=(nn, 54)) * 0.3).astype(np.float32)
        y = np.where(x[:, 0] + 0.2 * rng.standard_normal(len(x)) > 0,
                     1, -1).astype(np.int32)
        cfg = SVMConfig(c=2048.0, gamma=0.03125, epsilon=1e-3)

    q = args.q
    n, d = x.shape
    if args.roofline:
        return roofline(n, d, q, args.dtype, fixed_ms=args.fixed_ms)
    if args.ring or args.bf16_gram:
        from dpsvm_tpu.config import ObsConfig

        ocfg = ObsConfig(enabled=args.obs, runlog_dir=args.obs_dir)
        rc = 0
        if args.ring:
            rc |= ablate_ring(x, y, cfg, q, args.reps, args.sync_rounds,
                              args.dtype, obs_cfg=ocfg)
        if args.bf16_gram:
            rc |= ablate_bf16_gram(x, y, cfg, q, args.reps, obs_cfg=ocfg)
        return rc
    if args.shardlocal:
        return ablate_shardlocal(x, y, cfg, q, args.reps,
                                 args.sync_rounds, args.dtype)
    kp = KernelParams("rbf", cfg.resolve_gamma(d))
    valid_dev = None
    if args.fused or args.pipeline or args.fused_round:
        # The fused runner's contract: rows padded to 1024 with a valid
        # mask (solver/smo.py pads the same way).
        n_pad = -(-n // 1024) * 1024
        x_p = np.zeros((n_pad, d), np.float32)
        x_p[:n] = x
        y_p = np.ones((n_pad,), np.float32)
        y_p[:n] = y
        valid = np.zeros((n_pad,), bool)
        valid[:n] = True
        x, y = x_p, y_p
        valid_dev = jnp.asarray(valid)
        n = n_pad
        if q // 2 > n_pad // 128:
            ap.error(f"--fused/--pipeline/--fused-round need q/2 <= "
                     f"n_pad/128 (one candidate per 128-row per side): "
                     f"q={q}, n_pad={n_pad} allows q <= "
                     f"{2 * (n_pad // 128)}")
    xd = jnp.asarray(x, jnp.bfloat16 if args.dtype == "bfloat16"
                     else jnp.float32)
    yd = jnp.asarray(y, jnp.float32)
    x_sq = jax.jit(squared_norms)(xd)
    k_diag = jax.jit(kernel_diag, static_argnames="params")(x_sq, params=kp)
    rng = np.random.default_rng(1)
    alpha = jnp.asarray(np.clip(rng.normal(1.0, 1.0, n), 0, cfg.c),
                        jnp.float32)
    f = jnp.asarray(rng.normal(0, 1, n), jnp.float32)
    print(f"dataset={args.dataset} n={n} d={d} q={q} reps={args.reps}")

    c = cfg.c_bounds()

    if args.fused_round:
        # Fused-round-vs-stock-fused whole-chunk A/B (ISSUE 12 — the
        # measurement solver/block.py fused_round_pays is waiting on).
        # Trajectories are bitwise identical by construction
        # (tests/test_fused_round.py), so pairs match and the fixed
        # round cost is the decisive number.
        budgets = (tuple(int(v) for v in args.budgets.split(","))
                   if args.budgets else None)
        print("  whole-chunk ablation — STOCK fused engine (baseline):")
        rows_f, fix_f, marg_f = ablate(
            xd, yd, x_sq, k_diag, kp, cfg, q, args.reps, fused=True,
            valid=valid_dev, budgets=budgets)
        print("  whole-chunk ablation — ONE-PASS fused round:")
        rows_r, fix_r, marg_r = ablate(
            xd, yd, x_sq, k_diag, kp, cfg, q, args.reps,
            fusedround=True, valid=valid_dev, budgets=budgets)
        if fix_f > 0:
            print(f"  => fused-round fixed cost {fix_r:.3f} ms vs "
                  f"stock fused {fix_f:.3f} ms "
                  f"({fix_r / fix_f:.2f}x; flip solver/block.py "
                  f"fused_round_pays from THIS number, measured on a "
                  f"real device)")
        obs_log_rows("fused", rows_f, fix_f, marg_f)
        obs_log_rows("fusedround", rows_r, fix_r, marg_r)
        return 0

    if args.ablate_only:
        budgets = (tuple(int(v) for v in args.budgets.split(","))
                   if args.budgets else None)
        print("  whole-chunk ablation over inner budgets (authoritative):")
        rows_a, fixed_ms, marg_us = ablate(
            xd, yd, x_sq, k_diag, kp, cfg, q, args.reps,
            fused=args.fused, valid=valid_dev, budgets=budgets,
            pipelined=args.pipeline)
        stages = ("gather+gram+fused-fold/select+top-h+scatter"
                  if args.fused else
                  "prefetched select/gather/gram OVERLAPPED with the "
                  "chain; handoff+fold+scatter serial"
                  if args.pipeline else
                  "select+gather+gram+fold+scatter")
        print(f"  => fixed round cost {fixed_ms:.3f} ms ({stages}), "
              f"marginal {marg_us:.2f} us/pair")
        obs_log_rows("pipelined" if args.pipeline
                     else "fused" if args.fused else "plain",
                     rows_a, fixed_ms, marg_us)
        return 0

    # --- select
    def s_select(f, alpha):
        w, ok, b_hi, b_lo = select_block(f, alpha, yd, c, q)
        return f + 1e-20 * w[0], alpha  # data-dependence, no real change

    t_sel = timed(s_select, f, alpha, reps=args.reps)

    w, ok, _, _ = jax.jit(lambda f, a: select_block(f, a, yd, c, q))(f, alpha)

    # --- gather
    def s_gather(f, alpha):
        qx = jnp.take(xd, w, axis=0)
        qsq = jnp.take(x_sq, w)
        aw = jnp.take(alpha, w)
        yw = jnp.take(yd, w)
        fw = jnp.take(f, w)
        kdw = jnp.take(k_diag, w)
        return f + 1e-20 * (jnp.sum(qx.astype(jnp.float32)) + qsq[0]
                            + aw[0] + yw[0] + fw[0] + kdw[0]), alpha

    t_gather = timed(s_gather, f, alpha, reps=args.reps)

    qx = jax.jit(lambda: jnp.take(xd, w, axis=0))()
    qsq = jnp.take(x_sq, w)
    aw = jnp.take(alpha, w)
    yw = jnp.take(yd, w)
    fw = jnp.take(f, w)
    kdw = jnp.take(k_diag, w)

    # --- gram
    def s_gram(f, alpha):
        dots = jnp.dot(qx, qx.T, preferred_element_type=jnp.float32)
        kb = kernel_from_dots(dots, qsq, qsq, kp)
        return f + 1e-20 * kb[0, 0], alpha

    t_gram = timed(s_gram, f, alpha, reps=args.reps)

    kb = jax.jit(lambda: kernel_from_dots(
        jnp.dot(qx, qx.T, preferred_element_type=jnp.float32),
        qsq, qsq, kp))()

    # --- inner (pallas subproblem, full budget)
    def s_inner(f, alpha):
        aw2, t = solve_subproblem_pallas(
            kb, aw, yw, fw, kdw, ok.astype(jnp.float32),
            jnp.int32(q), c, float(cfg.epsilon), float(cfg.tau))
        return f + 1e-20 * (aw2[0] + t), alpha

    t_inner = timed(s_inner, f, alpha, reps=max(20, args.reps // 4))

    # --- fold
    coef = jnp.asarray(rng.normal(0, 0.1, q), jnp.float32)

    def s_fold(f, alpha):
        k_rows = kernel_rows(xd, x_sq, qx, qsq, kp)
        return f + coef @ k_rows, alpha

    t_fold = timed(s_fold, f, alpha, reps=args.reps)

    # --- scatter (the round's extrema now ride the selection pass)
    def s_scatter(f, alpha):
        safe_w = jnp.where(ok, w, jnp.int32(n))
        alpha = alpha.at[safe_w].set(jnp.where(ok, aw, 0.0), mode="drop")
        return f + 1e-20 * alpha[0], alpha

    t_scatter = timed(s_scatter, f, alpha, reps=args.reps)

    # --- full round for comparison
    from dpsvm_tpu.solver.block import BlockState, run_chunk_block

    st = BlockState(alpha=alpha, f=f, b_hi=jnp.float32(-1e9),
                    b_lo=jnp.float32(1e9), pairs=jnp.int32(0),
                    rounds=jnp.int32(0))
    runner = lambda st: run_chunk_block(
        xd, yd, x_sq, k_diag, None, st, jnp.int32(10**9), kp, c,
        float(cfg.epsilon), float(cfg.tau), q, q, args.reps,
        inner_impl="pallas")
    out = runner(st)  # compile + warm
    jax.block_until_ready(out)
    # Time a SECOND execution from an (epsilon-perturbed) fresh state:
    # continuing from the warmed-up state would run degenerate
    # near-converged rounds, and re-dispatching the IDENTICAL state lets
    # the tunnel serve the cached result without executing (measured
    # ~0 ms) — hence the off-clock salt.
    st2 = st._replace(f=salted(st.f, 1))
    t0 = time.perf_counter()
    out2 = runner(st2)
    jax.block_until_ready(out2)
    t_full = (time.perf_counter() - t0) / max(int(out2.rounds), 1)
    print(f"  (full-round chunk executed {int(out2.rounds)} rounds, "
          f"{int(out2.pairs)} pairs)")

    total = t_sel + t_gather + t_gram + t_inner + t_fold + t_scatter
    print("  isolated stages (differenced fori_loop probes — INDICATIVE "
          "only; the tunnel's dispatch elision/latency can distort them):")
    for name, t in [("select", t_sel), ("gather", t_gather),
                    ("gram", t_gram), ("inner(pallas)", t_inner),
                    ("fold", t_fold), ("scatter", t_scatter),
                    ("SUM", total), ("FULL ROUND", t_full)]:
        print(f"  {name:15s} {1e3 * t:8.3f} ms")

    # Whole-chunk ablation: the authoritative attribution (see ablate()).
    print("  whole-chunk ablation over inner budgets (authoritative):")
    rows, fixed_ms, marg_us = ablate(xd, yd, x_sq, k_diag, kp, cfg, q,
                                     args.reps, fused=args.fused,
                                     valid=valid_dev)
    stages = ("gather+gram+fused-fold/select+top-h+scatter" if args.fused
              else "select+gather+gram+fold+scatter")
    print(f"  => fixed round cost {fixed_ms:.3f} ms ({stages}), marginal "
          f"{marg_us:.2f} us/pair (serial subproblem chain)")
    obs_log_rows("fused" if args.fused else "plain", rows, fixed_ms,
                 marg_us)
    return 0


if __name__ == "__main__":
    sys.exit(main())
