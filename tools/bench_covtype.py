"""Covtype-scale benchmark -> BENCH_COVTYPE.md (+ one JSON line).

The reference's stress configuration is covtype: n=500,000 x d=54,
c=2048, gamma=0.03125, eps=0.001, max_iter=3,000,000 over 10 GPUs
(reference Makefile:77). The real covtype CSV is not shipped in this
image; this benchmark runs the SAME shape/hyperparameters on a seeded
synthetic stand-in (identical construction to
tests/test_scale_and_debug.py) so the number is reproducible:

    rng = np.random.default_rng(0)
    x = rng.normal(size=(500000, 54)) * 0.3
    y = sign(x[:, 0] + 0.2 * N(0,1))

Two modes:

* default — the headline artifact: run the best-known config to the
  reference's full 3M-pair budget, recording a gap-vs-pairs trajectory
  (per-chunk callback) and the final TRAIN ACCURACY, so the throughput
  number is anchored to optimization quality (a pairs/s figure on an
  unconverged run proves speed, not usefulness).
* --sweep — the operating-point study: short (--sweep-pairs) runs over
  (selection in {mvp, second_order}) x (q, inner) x shrinking, ranked by
  device seconds to reach the common reachable gap. PROFILE.md explains
  why large inner budgets are the lever (the round is dominated by its
  fixed O(n) cost; the serial chain is ~0.5 us/pair): pairs on a stale
  working set are cheap but less useful, so the sweep ranks by
  TIME-TO-GAP, never raw pairs/s.

Run on the real TPU: `python tools/bench_covtype.py [--sweep]`
(default mode rewrites BENCH_COVTYPE.md at the repo root).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

N, D = 500_000, 54
MAX_ITER = 3_000_000  # the reference's covtype budget (Makefile:77)


def make_data():
    rng = np.random.default_rng(0)
    x = (rng.normal(size=(N, D)) * 0.3).astype(np.float32)
    y = np.where(x[:, 0] + 0.2 * rng.standard_normal(N) > 0, 1, -1).astype(
        np.int32)
    return x, y


def sweep(x, y, base, budget: int):
    """Budget-ladder study: each config runs UNOBSERVED (single
    dispatch, device-clean time) to budget/5, 2/5 budget and the full
    budget; the gap at each exit comes from the solver's host-side
    extrema refresh. Chunked per-chunk observation was measured to
    charge configs UNEVENLY (~70-80 ms tunnel latency per dispatch,
    and configs whose subproblems exit early pay more dispatches per
    pair), which inverted the pairs/s ordering vs PROFILE.md's
    single-dispatch ablation — the ladder gives every probe exactly one
    dispatch."""
    from dpsvm_tpu.solver.smo import solve

    grid = []
    for sel in ("mvp", "second_order"):
        # pair_batch is mvp-only; second_order rows run single-pair. An
        # explicit mvp/pb1 row keeps the batching win visible in the
        # ranking instead of baked invisibly into every mvp row.
        pb = base.pair_batch if sel == "mvp" else 1
        for q, inner in ((512, 2048), (512, 4096), (512, 16384),
                         (1024, 4096), (1024, 8192)):
            grid.append(base.replace(selection=sel, working_set_size=q,
                                     inner_iters=inner, pair_batch=pb))
        # Shrinking rows (PROFILE.md: the fixed cost is the bottleneck;
        # shrinking divides its O(n) terms by n/m for k_rounds per cycle).
        grid.append(base.replace(selection=sel, working_set_size=512,
                                 inner_iters=2048, active_set_size=65536,
                                 reconcile_rounds=8, pair_batch=pb))
    if base.pair_batch != 1:
        # Explicit single-pair control row so the batching win stays
        # visible in the ranking (skipped if base already runs pb1,
        # which would duplicate a loop row above).
        grid.append(base.replace(selection="mvp", working_set_size=512,
                                 inner_iters=16384, pair_batch=1))
    # pb4 ranking rows (VERDICT round-5 weak #2): the block subproblem's
    # 4-slot batched variant at the two best operating points. pb8 is
    # NOT rankable on this dataset — it exists only on the per-pair
    # micro executor, which at n=500k has no resident Gram to lean on
    # (1 TB); tools/sweep_block.py --micro-pb ranks it at the 60k shape.
    for q, inner in ((512, 2048), (512, 4096)):
        grid.append(base.replace(selection="mvp", working_set_size=q,
                                 inner_iters=inner, pair_batch=4))
    ladder = [budget // 5, 2 * budget // 5, budget]
    results = []  # (label, cfg, points=[(pairs, gap, dev_s), ...])
    for cfg in grid:
        label = (f"{cfg.selection}/q{cfg.working_set_size}"
                 f"/i{cfg.inner_iters}"
                 + (f"/m{cfg.active_set_size}" if cfg.active_set_size else "")
                 + f"/pb{cfg.pair_batch}")
        solve(x, y, cfg.replace(max_iter=64))  # compile (same executor)
        points = []
        for b in ladder:
            res = solve(x, y, cfg.replace(max_iter=b))
            points.append((int(res.iterations),
                           float(res.b_lo - res.b_hi),
                           res.train_seconds))
        results.append((label, cfg, points))
        print(f"[{label}] " + "  ".join(
            f"{p}p/{g:.3f}g/{t:.2f}s" for p, g, t in points), flush=True)

    def seconds_to_gap(points, g):
        for p, gap, t in points:
            if gap <= g:
                return t
        return None

    # Rank by device seconds to a DISCRIMINATING target: 110% of the
    # best full-budget gap (runs that never reach it rank last by
    # final gap).
    best_gap = min(pts[-1][1] for _, _, pts in results)
    target = max(1.1 * best_gap, 2 * base.epsilon)
    ranked = sorted(
        results,
        key=lambda e: (seconds_to_gap(e[2], target)
                       or 1e9 + e[2][-1][1]))
    print(f"\nsweep ranking (device s to gap <= {target:.4f}, "
          f"ladder {ladder} pairs):")
    lines = [f"Budget ladder {ladder} pairs/config, each point one "
             f"unobserved dispatch. Ranked by device seconds to reach "
             f"gap <= {target:.4f} (110% of the best full-budget gap); "
             f"runs that never reach it rank last by final gap.", "",
             "| config | s to target gap | final gap | pairs | dev s | "
             "pairs/s |", "|---|---|---|---|---|---|"]
    for label, cfg, pts in ranked:
        s = seconds_to_gap(pts, target)
        pairs, gap, t = pts[-1]
        pps = pairs / max(t, 1e-9)
        print(f"  {label:28s} "
              f"{f'{s:.2f}' if s is not None else '-':>8} "
              f"gap={gap:8.4f} pairs={pairs} dev_s={t:.2f}")
        lines.append(
            f"| {label} | {f'{s:.2f}' if s is not None else '—'} | "
            f"{gap:.4f} | {pairs} | {t:.2f} | {pps:,.0f} |")
    return ranked, lines


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--sweep", action="store_true")
    ap.add_argument("--sweep-pairs", type=int, default=768_000)
    args = ap.parse_args()

    import jax

    from dpsvm_tpu.config import SVMConfig

    x, y = make_data()

    # Operating point from the --sweep ranking (2026-07-30, re-ranked
    # 2026-07-31 with pair_batch): mvp with a large inner budget
    # amortizes the fixed round cost (PROFILE.md) over every pair the
    # working set can absorb; the subproblem exits when the local gap
    # closes (~1.3k useful pairs per q=512 set at this extreme-C shape,
    # PROFILE.md round-4 section), so the budget is a ceiling, not a
    # forcing, and i2048-i16384 rank within drift of each other.
    # WSS2 measured SLOWER at equal quality on
    # both this shape and adult-shape (the block engine's pair
    # redundancy comes from working-set restriction, not partner choice
    # within W; see BENCH_COVTYPE_SWEEP.md) — defaults stay mvp.
    # dtype=float32: at THIS gamma (0.03125, pairwise distances^2
    # clustered ~9.7) the discriminative signal is ~1% variations around
    # K~0.74, which bf16 X rounding destroys — measured on a 20k
    # subsample at 50M pairs: fp32 reaches train acc 0.973, bf16 0.593
    # at the same pair count (speed is identical: 912k vs 900k pairs/s).
    # The mnist-shaped headline bench keeps bf16, where its quality gate
    # passes; this is a per-shape numerics decision, not a default.
    # pair_batch=2 (SVMConfig.pair_batch): same-session A/B at this exact
    # config measured 2.822 s vs 3.152 s (+12% pairs/s) with a BETTER
    # final gap at the same pair count (4.74 vs 4.82) — the batched
    # second slot is an exact update, so it buys pure throughput here.
    base = SVMConfig(
        c=2048.0, gamma=0.03125, epsilon=1e-3, max_iter=MAX_ITER,
        cache_lines=0, engine="block", working_set_size=512,
        inner_iters=16384, selection="mvp", dtype="float32",
        pair_batch=2)

    if args.sweep:
        _, lines = sweep(x, y, base, args.sweep_pairs)
        out = os.path.join(REPO, "BENCH_COVTYPE_SWEEP.md")
        with open(out, "w") as fh:
            fh.write("# BENCH_COVTYPE_SWEEP — operating-point study\n\n"
                     "Command: `python tools/bench_covtype.py --sweep` "
                     "(real TPU).\n\n" + "\n".join(lines) + "\n")
        print(f"wrote {out}", file=sys.stderr)
        return 0

    from dpsvm_tpu.solver.smo import solve

    solve(x, y, base.replace(max_iter=64))  # compile
    # Headline time: ONE unobserved dispatch of the full budget (chunked
    # observation pays ~70-80 ms tunnel latency per chunk and was
    # measured to distort config comparisons; see sweep()). The
    # trajectory comes from a ladder of shorter unobserved runs — each
    # point an independent solve from the zero start, so its time is
    # directly the device-seconds-to-that-many-pairs. The headline value
    # is best of three (bench.py discipline): the tunneled harness shows
    # ~+-20% run-to-run/session drift (e.g. the same headline-bench
    # config read 0.135 s and 0.165 s twenty minutes apart,
    # PROFILE.md round-4 section). NOTE: do not "confirm" drift by
    # comparing against budget_mode runs — those execute full
    # inner-budget rounds (1.6M pairs/s at this shape) and measure a
    # different thing than this honest-eps run (~945k), see PROFILE.md.
    runs = [solve(x, y, base) for _ in range(3)]
    res = min(runs, key=lambda r: r.train_seconds)
    traj_rows = []
    for b in (250_000, 500_000, 1_000_000, 1_500_000, 2_000_000,
              2_500_000):
        r = solve(x, y, base.replace(max_iter=b))
        traj_rows.append((int(r.iterations), float(r.b_lo - r.b_hi),
                          r.train_seconds))
        print(f"  ladder {r.iterations} pairs: gap="
              f"{float(r.b_lo - r.b_hi):.5f} {r.train_seconds:.2f}s",
              file=sys.stderr)
    traj_rows.append((int(res.iterations), float(res.b_lo - res.b_hi),
                      res.train_seconds))

    # Quality anchors: final train accuracy (the reference prints its own
    # train accuracy after covtype runs, svmTrainMain.cpp:335), the gap
    # trajectory, and a 20k-subsample run at a per-row-comparable budget
    # showing the machinery optimizes to high accuracy.
    from dpsvm_tpu.models.svm_model import SVMModel
    from dpsvm_tpu.ops.kernels import KernelParams
    from dpsvm_tpu.predict import accuracy

    kp = KernelParams("rbf", base.resolve_gamma(D))
    model = SVMModel.from_dense(x, y, res.alpha, res.b, kp)
    acc = accuracy(model, x, y)

    xs, ys = x[:20_000], y[:20_000]
    cfg20 = base.replace(max_iter=50_000_000, inner_iters=4096)
    solve(xs, ys, cfg20.replace(max_iter=64))  # compile (new n shape)
    r20 = solve(xs, ys, cfg20)
    m20 = SVMModel.from_dense(xs, ys, r20.alpha, r20.b, kp)
    acc20 = accuracy(m20, xs, ys)

    dev = str(jax.devices()[0])
    pps = res.iterations / max(res.train_seconds, 1e-9)
    line = {
        "metric": (
            f"synthetic covtype-shaped 500kx54 RBF modified-SMO, 1 chip, "
            f"c=2048 gamma=0.03125 eps=0.001 (reference stress config, "
            f"Makefile:77; budget {MAX_ITER} pair updates)"),
        "value": round(res.train_seconds, 3),
        "unit": "seconds",
        "pair_updates": int(res.iterations),
        "pairs_per_second": round(pps),
        "converged": bool(res.converged),
        "final_gap": round(float(res.b_lo - res.b_hi), 6),
        "train_accuracy": round(float(acc), 4),
        "subsample20k_50M_train_accuracy": round(float(acc20), 4),
        "n_sv": int(res.n_sv),
        "pair_batch": int(base.pair_batch),
        "device": dev,
    }
    print(json.dumps(line))

    md = [
        "# BENCH_COVTYPE — covtype-scale artifact",
        "",
        "Command: `python tools/bench_covtype.py` (real TPU; synthetic",
        "covtype-shaped data, generation pinned in the tool's docstring;",
        "operating point from BENCH_COVTYPE_SWEEP.md).",
        "",
        f"* device: {dev}",
        f"* config: n={N} d={D} c={base.c:g} gamma={base.gamma:g} "
        f"eps={base.epsilon:g} engine={base.engine} "
        f"selection={base.selection} q={base.working_set_size} "
        f"inner={base.inner_iters} dtype={base.dtype} "
        f"pair_batch={base.pair_batch}, "
        f"max_iter={MAX_ITER} (reference Makefile:77 budget)",
        f"* pair updates: **{res.iterations}** "
        f"(converged={res.converged}, final gap "
        f"{float(res.b_lo - res.b_hi):.6f})",
        f"* device solve time: **{res.train_seconds:.1f} s** "
        f"({pps:,.0f} pair updates/s)",
        f"* support vectors: {res.n_sv}",
        f"* train accuracy at the 3M budget: **{acc:.4f}** — honest "
        "context: the reference's own covtype cap is 3M pair updates "
        "for n=500k (6 updates/row), far below what c=2048 needs; the "
        "reference publishes no covtype accuracy or wall-clock either "
        "(Makefile:77 is the only trace). The anchor below shows the "
        "same solver reaching high accuracy when the per-row budget is "
        "realistic.",
        f"* quality verification (20k subsample of the same "
        f"distribution, same hyperparameters, 50M pairs = 2500/row): "
        f"train accuracy **{acc20:.4f}** in {r20.train_seconds:.1f} s "
        f"device time (fp32; the same run with bf16 X reaches only "
        f"0.59 — at gamma=0.03125 the kernel's discriminative signal "
        f"is ~1% variations that bf16 rounding destroys, which is why "
        f"this benchmark pins dtype=float32).",
        "",
        "Engine-semantics note (measured 2026-07-30, committed for "
        "honesty): this table's 'pair updates' are block-subproblem "
        "pairs — cheaper and less globally informed than the per-pair "
        "engine's global-MVP iterations, which are what the reference's "
        "max_iter counts. At n=50k of this distribution the per-pair "
        "engine reaches gap 0.026 by 8M pairs (22 us/pair) while the "
        "block engine's restricted working sets cycle at the tail of "
        "this extreme-C problem (gap ~3 after 460M subproblem pairs). "
        "The block engine is the right tool for the throughput budget "
        "regime benchmarked here and matches per-pair optima at "
        "moderate C (PARITY.md); for extreme-C runs to tight gaps, use "
        "engine='xla' (the covtype-shaped PARITY.md row does).",
        "",
        "Gap-vs-pairs trajectory (each row an independent unobserved "
        "run from the zero start to that pair budget; time is "
        "device-seconds to reach it; ladder rows are single runs, the "
        "final full-budget row is the best of three — with the "
        "tunnel's ~+-20% session drift the mixed estimators can read "
        "non-monotonic near the top):",
        "",
        "| pair updates | KKT gap (b_lo - b_hi) | device s |",
        "|---|---|---|",
    ]
    md += [f"| {it} | {gap:.5f} | {t:.2f} |" for it, gap, t in traj_rows]
    md += ["", "```json", json.dumps(line), "```", ""]
    out = os.path.join(REPO, "BENCH_COVTYPE.md")
    # Preserve the full-n quality-trajectory section that
    # tools/covtype_fullscale.py appends (a 47-min measured artifact —
    # a header refresh must never clobber it; it did once, 2026-07-31).
    keep = ""
    if os.path.exists(out):
        text = open(out).read()
        idx = text.find("## full-n quality trajectory")
        if idx >= 0:
            keep = text[idx:]
    tmp = out + ".tmp"
    with open(tmp, "w") as fh:
        fh.write("\n".join(md))
        if keep:
            fh.write("\n" + keep)
    os.replace(tmp, out)  # atomic: never leave the artifact truncated
    print(f"wrote {out}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
