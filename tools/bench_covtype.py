"""Covtype-scale benchmark -> BENCH_COVTYPE.md (+ one JSON line).

The reference's stress configuration is covtype: n=500,000 x d=54,
c=2048, gamma=0.03125, eps=0.001, max_iter=3,000,000 over 10 GPUs
(reference Makefile:77). The real covtype CSV is not shipped in this
image; this benchmark runs the SAME shape/hyperparameters on a seeded
synthetic stand-in (identical construction to
tests/test_scale_and_debug.py) so the number is reproducible:

    rng = np.random.default_rng(0)
    x = rng.normal(size=(500000, 54)) * 0.3
    y = sign(x[:, 0] + 0.2 * N(0,1))

It substantiates docs/ARCHITECTURE.md's covtype-scale claim (block
engine: ~3M pair updates in tens of seconds on one v5e chip) with a
committed artifact. Run on the real TPU: `python tools/bench_covtype.py`
(writes BENCH_COVTYPE.md at the repo root).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

N, D = 500_000, 54
MAX_ITER = 3_000_000  # the reference's covtype budget (Makefile:77)


def main() -> int:
    import jax

    from dpsvm_tpu.config import SVMConfig
    from dpsvm_tpu.solver.smo import solve

    rng = np.random.default_rng(0)
    x = (rng.normal(size=(N, D)) * 0.3).astype(np.float32)
    y = np.where(x[:, 0] + 0.2 * rng.standard_normal(N) > 0, 1, -1).astype(
        np.int32)

    # chunk_iters + a (no-op) callback split the solve into ~12 dispatches
    # of ~250k pair updates: a single 3M-pair dispatch (~50k while_loop
    # rounds) faults the tunneled device runtime, and chunk boundaries
    # also give the run a heartbeat. The ~80 ms observation cost per chunk
    # is noise against the ~tens-of-seconds solve.
    # q=512 with a 4q inner budget measured best at this n in the
    # tools/sweep_block.py grid (~636k pair updates/s).
    config = SVMConfig(
        c=2048.0, gamma=0.03125, epsilon=1e-3, max_iter=MAX_ITER,
        cache_lines=0, engine="block", working_set_size=512,
        inner_iters=2048, dtype="bfloat16", chunk_iters=250_000)

    def heartbeat(it, b_hi, b_lo, state):
        print(f"  ... {it} pairs, gap={b_lo - b_hi:.5f}", file=sys.stderr)

    # Warm-up compiles the chunk executor (max_iter is traced, so a short
    # run builds the same program the timed run uses).
    solve(x, y, config.replace(max_iter=64), callback=heartbeat)
    t0 = time.perf_counter()
    res = solve(x, y, config, callback=heartbeat)
    wall = time.perf_counter() - t0

    dev = str(jax.devices()[0])
    pps = res.iterations / max(res.train_seconds, 1e-9)
    line = {
        "metric": (
            f"synthetic covtype-shaped 500kx54 RBF modified-SMO, 1 chip, "
            f"c=2048 gamma=0.03125 eps=0.001 (reference stress config, "
            f"Makefile:77; budget {MAX_ITER} pair updates)"),
        "value": round(res.train_seconds, 3),
        "unit": "seconds",
        "pair_updates": int(res.iterations),
        "pairs_per_second": round(pps),
        "converged": bool(res.converged),
        "final_gap": round(float(res.b_lo - res.b_hi), 6),
        "n_sv": int(res.n_sv),
        "device": dev,
    }
    print(json.dumps(line))

    md = [
        "# BENCH_COVTYPE — covtype-scale artifact",
        "",
        "Command: `python tools/bench_covtype.py` (real TPU; synthetic",
        "covtype-shaped data, generation pinned in the tool's docstring).",
        "",
        f"* device: {dev}",
        f"* config: n={N} d={D} c={config.c:g} gamma={config.gamma:g} "
        f"eps={config.epsilon:g} engine={config.engine} "
        f"q={config.working_set_size} inner={config.inner_iters} "
        f"dtype={config.dtype}, max_iter={MAX_ITER} "
        "(reference Makefile:77 budget)",
        f"* pair updates: **{res.iterations}** "
        f"(converged={res.converged}, final gap "
        f"{float(res.b_lo - res.b_hi):.6f})",
        f"* device solve time: **{res.train_seconds:.1f} s** "
        f"({pps:,.0f} pair updates/s); wall incl. host: {wall:.1f} s",
        f"* support vectors: {res.n_sv}",
        "",
        "```json",
        json.dumps(line),
        "```",
        "",
    ]
    out = os.path.join(REPO, "BENCH_COVTYPE.md")
    with open(out, "w") as fh:
        fh.write("\n".join(md))
    print(f"wrote {out}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
