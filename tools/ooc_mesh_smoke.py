"""Mesh out-of-core smoke (ISSUE 19) — `make ooc_mesh_smoke`, wired
into tier1.yml.

Two checks on the 2-virtual-device CPU harness, end to end:

1. **Bitwise parity** — solve_mesh + config.ooc at num_devices=2 must
   land BITWISE on the single-chip ooc stream's final state (alpha, f,
   b_hi/b_lo, iteration count). This is the acceptance criterion
   verbatim: each lane's fold is the same fold_tile_body op sequence
   at the same (tile,) shapes and the round joins on exactly one
   (q, 5) scalar psum, so equality is exact, not approximate.
2. **Stream fault seam** — the `ooc_tile_put` seam fires on the mesh
   stream's per-step device_put too (ISSUE 13 composition): a planned
   transient fault mid-stream with retry_faults=1 must be absorbed by
   the shared retry machinery and land on the SAME bitwise state.

Needs 2 visible devices; run through the Makefile target, which forces
JAX_PLATFORMS=cpu with --xla_force_host_platform_device_count=2. No
artifacts written; exit 0 = both behaviors held.
"""

from __future__ import annotations

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

N, D, SEED, SEP = 1024, 24, 11, 1.0


def _cfg(**kw):
    from dpsvm_tpu.config import SVMConfig

    base = dict(c=2.0, epsilon=1e-3, engine="block",
                working_set_size=64, max_iter=50_000,
                ooc=True, ooc_tile_rows=256)
    base.update(kw)
    return SVMConfig(**base)


def main() -> int:
    import jax
    import numpy as np

    have = len(jax.devices())
    if have < 2:
        print(f"[ooc_mesh_smoke] FAIL: needs 2 devices, found {have} "
              "(run via `make ooc_mesh_smoke`)")
        return 1

    from dpsvm_tpu.data.synth import make_blobs_binary
    from dpsvm_tpu.parallel.dist_smo import solve_mesh
    from dpsvm_tpu.solver.smo import solve
    from dpsvm_tpu.testing import faults

    x, y = make_blobs_binary(n=N, d=D, seed=SEED, sep=SEP)

    single = solve(x, y, _cfg())
    assert single.converged, "single-chip ooc reference did not converge"
    mesh = solve_mesh(x, y, _cfg(), num_devices=2)
    assert mesh.converged, "mesh ooc did not converge"
    assert mesh.stats.get("ooc_mesh") is True, mesh.stats.get("ooc_mesh")

    assert mesh.iterations == single.iterations, (
        f"iteration divergence: mesh={mesh.iterations} "
        f"single={single.iterations}")
    np.testing.assert_array_equal(mesh.alpha, single.alpha)
    np.testing.assert_array_equal(mesh.stats["f"], single.stats["f"])
    assert mesh.b_hi == single.b_hi and mesh.b_lo == single.b_lo
    print(f"[ooc_mesh_smoke] mesh(2) BITWISE == single-chip ooc "
          f"({single.iterations} pairs, n={N}) OK")

    # The ooc_tile_put seam must cover the mesh stream's H2D path:
    # one planned transient fault mid-stream, absorbed by the shared
    # retry machinery, landing on the same bitwise state.
    with faults.install(faults.FaultPlan.parse("ooc_tile_put@3")) as plan:
        retried = solve_mesh(x, y, _cfg(retry_faults=1), num_devices=2)
    assert plan.fired.get("ooc_tile_put", 0) >= 1, (
        "ooc_tile_put seam never fired on the mesh stream")
    assert retried.converged
    assert retried.iterations == single.iterations
    np.testing.assert_array_equal(retried.alpha, single.alpha)
    np.testing.assert_array_equal(retried.stats["f"], single.stats["f"])
    print("[ooc_mesh_smoke] ooc_tile_put fault on the mesh stream "
          "retried to the same bitwise state OK")

    print("[ooc_mesh_smoke] ALL OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
