"""Shared helpers for the parity harnesses (parity60k / parity_covtype).

One implementation of the duplicate-merged SV metric and the PARITY.md
section splice, so the full-scale and covtype-shaped sections can never
drift onto different rules.
"""

from __future__ import annotations

import numpy as np


def merged_sv(x: np.ndarray, y: np.ndarray, alpha: np.ndarray) -> int:
    """Duplicate-merged SV count: sum |alpha| over identical (row, label)
    groups first — with duplicates the dual optimum is a face and the raw
    count is solver-path-dependent (see tools/parity.py methodology)."""
    _, inv = np.unique(x, axis=0, return_inverse=True)
    group = inv.astype(np.int64) * 2 + (y > 0)
    s = np.zeros(group.max() + 1)
    np.add.at(s, group, np.abs(alpha))
    return int((s > 0).sum())


def replace_section(path: str, section: str, lines: list) -> None:
    """Idempotently replace (or append) one '## ...' section of a
    markdown file. `section` is the exact heading line; `lines` the full
    replacement including that heading."""
    text = open(path).read()
    if section in text:
        head, rest = text.split(section, 1)
        tail = rest.split("\n## ", 1)
        text = head.rstrip("\n") + ("\n\n## " + tail[1].lstrip("\n")
                                     if len(tail) > 1 else "")
    open(path, "w").write(text.rstrip("\n") + "\n\n" + "\n".join(lines))

# The section headings of the surgically-maintained PARITY.md sections
# (tools/parity60k.py, tools/parity_covtype.py import these; the
# mid-scale rewriter tools/parity.py preserves everything from the
# earliest of them). ONE source of truth: a rename here keeps writer and
# preserver in sync — a drifted hardcoded copy would let a mid-scale
# refresh silently delete the measured full-scale/covtype artifacts.
SECTION_60K = ("## mnist-shaped / full-scale "
               "(n=60000, achieved KKT gap 1e-3; SV parity asserted)")
SECTION_COVTYPE = ("## covtype-shaped / subsampled "
                   "(achieved KKT gap 1e-3; SV parity asserted)")


def preserved_tail(text: str) -> str:
    """The trailing part of PARITY.md owned by the surgical writers
    (everything from the earliest preserved heading), or ""."""
    cuts = []
    for sec in (SECTION_60K, SECTION_COVTYPE):
        prefix = sec.split(" (")[0]
        if text.startswith(prefix):
            cuts.append(0)
        i = text.find("\n" + prefix)  # line-anchored: a prose mention of
        if i >= 0:                     # the heading must not become a cut
            cuts.append(i + 1)
    return text[min(cuts):] if cuts else ""
