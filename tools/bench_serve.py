"""Serving benchmark: compacted-vs-stacked A/B + offered-load sweep.

The inference artifact discipline (BENCH_PREDICT.md) covers the binary
decision path; this tool covers the MULTICLASS and request-serving
paths the serving engine (dpsvm_tpu/serve.py) owns:

* compacted-vs-stacked A/B at the two reference-adjacent multiclass
  shapes (MNIST-shaped 10-class OvO: 45 submodels x d=784;
  covtype-shaped 7-class OvR: d=54), with kernel-matmul FLOPs pinned
  BOTH analytically and from XLA's own compiled cost analysis —
  FLOP counts and HLO structure are platform-independent, so the ~k x
  reduction is adjudicable even on the CPU harness;
* an offered-load sweep through PredictServer (bucketed micro-batching)
  producing throughput and p50/p95/p99 latency per bucket.

Writes BENCH_SERVE_r<NN>.json at the repo root (commit it — the
artifact, not the commit message, is the evidence) and REWRITES
BENCH_SERVE.md with the current build's numbers. The headline metric
(examples_per_second, MNIST-OvO serving sweep) runs through the same
drift-normalized cross-session regression gate as the training bench
(bench._regression_gate, generalized over artifact pattern/metric key),
so serving numbers get the adjudication training got in PR 2.

Wall-clock numbers measured on a CPU harness are recorded with
device_numbers="pending" — per the repo's measurement discipline the
next TPU session re-runs this tool for publishable device numbers; the
FLOP/structure facts stand either way.

Run: `python tools/bench_serve.py [--pool N] [--requests N]`.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _synthetic_multiclass(n_classes: int, d: int, pool: int,
                          sv_frac: float, strategy: str, gamma: float,
                          seed: int, alpha_scale: float = 1.0):
    """A realistic shared-SV ensemble WITHOUT a training run: pool rows
    play the training matrix, each submodel's SVs are a sampled subset
    of its classes' rows (ascending row order, exactly what
    SVMModel.from_dense produces), coefficients are random. Serving cost
    depends only on these shapes, not on how the alphas were found —
    the same synthetic-SV discipline as tools/bench_predict.py."""
    from dpsvm_tpu.models.multiclass import MulticlassSVM
    from dpsvm_tpu.models.svm_model import SVMModel
    from dpsvm_tpu.ops.kernels import KernelParams

    rng = np.random.default_rng(seed)
    x = rng.random((pool, d), np.float32)
    cls = np.arange(pool) % n_classes  # row class assignment
    kp = KernelParams("rbf", gamma)
    models = []
    if strategy == "ovo":
        splits = [(a, b) for a in range(n_classes)
                  for b in range(a + 1, n_classes)]
    else:
        splits = [(a, None) for a in range(n_classes)]
    for a, b in splits:
        rows = (np.nonzero((cls == a) | (cls == b))[0] if b is not None
                else np.arange(pool))
        take = rng.random(len(rows)) < sv_frac
        idx = rows[take]
        n_sv = len(idx)
        models.append(SVMModel(
            sv_x=x[idx],
            sv_alpha=(rng.random(n_sv).astype(np.float32) + 0.01)
            * np.float32(alpha_scale),
            sv_y=np.where(rng.random(n_sv) < 0.5, 1, -1).astype(np.int32),
            b=float(rng.normal() * 0.1),
            kernel=kp))
    m = MulticlassSVM(classes=np.arange(n_classes), models=models,
                      strategy=strategy)
    m.ensure_compacted(x_train=x)
    return m


def _executor_flops(fn, *shapes_and_statics) -> float:
    """Total FLOPs of one compiled executor call, from XLA's own cost
    analysis (platform-independent structure fact)."""
    lowered = fn.lower(*shapes_and_statics[:-1], **shapes_and_statics[-1])
    cost = lowered.compile().cost_analysis()
    if isinstance(cost, (list, tuple)):  # older jax returns [dict]
        cost = cost[0]
    return float(cost.get("flops", float("nan")))


def _ab_record(m, nb: int, label: str) -> dict:
    """Compacted-vs-stacked A/B at one ensemble shape: analytic kernel
    FLOPs, compiled total FLOPs, and best-of-3 wall time per path."""
    import jax
    import jax.numpy as jnp

    from dpsvm_tpu.models import multiclass as mc

    ens = m.compacted
    k = len(m.models)
    d = m.models[0].sv_x.shape[1]
    m_pad = ens.m_pad
    s_union = int(ens.sv_union.shape[0])  # incl. the trailing pad row
    kp = ens.kernel
    sds = jax.ShapeDtypeStruct

    stacked_fn = mc._stacked_batch_factory()
    compact_fn = mc._compacted_batch_factory()
    f_stacked = _executor_flops(
        stacked_fn, sds((nb, d), jnp.float32),
        sds((k, m_pad, d), jnp.float32), sds((k, m_pad), jnp.float32),
        sds((k,), jnp.float32), {"kp": kp})
    f_compact = _executor_flops(
        compact_fn, sds((nb, d), jnp.float32),
        sds((s_union, d), jnp.float32), sds((k, m_pad), jnp.float32),
        sds((k, m_pad), jnp.int32), sds((k,), jnp.float32), {"kp": kp})

    rng = np.random.default_rng(11)
    q = rng.random((nb, d), np.float32)

    def best_of(path):
        mc.decision_matrix(m, q, path=path)  # warm (compile + upload)
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            mc.decision_matrix(m, q, path=path)
            best = min(best, time.perf_counter() - t0)
        return best

    t_stacked = best_of("stacked")
    t_compact = best_of("compacted")
    parity = np.array_equal(mc.decision_matrix(m, q, path="stacked"),
                            mc.decision_matrix(m, q, path="compacted"))
    # Kernel-matmul FLOPs (the dominant term the compaction attacks):
    # stacked evaluates k replicated (nb, m_pad, d) products, compacted
    # ONE (nb, S, d) product.
    ker_stacked = 2.0 * nb * d * k * m_pad
    ker_compact = 2.0 * nb * d * s_union
    return {
        "shape": label, "n_models": k, "d": d, "m_pad": m_pad,
        "sv_union": ens.n_union,
        "total_sv_stacked": int(ens.counts.sum()),
        "query_block": nb,
        "kernel_flops_stacked": ker_stacked,
        "kernel_flops_compacted": ker_compact,
        "kernel_flop_reduction": round(ker_stacked / ker_compact, 2),
        "xla_flops_stacked": f_stacked,
        "xla_flops_compacted": f_compact,
        "xla_flop_reduction": round(f_stacked / f_compact, 2),
        "wall_seconds_stacked_best3": round(t_stacked, 4),
        "wall_seconds_compacted_best3": round(t_compact, 4),
        "bit_identical": bool(parity),
    }


def _storage_ab(serve_cfg, requests: int, pool: int) -> list:
    """f32-vs-bf16-vs-int8 union-storage frontier at ONE matched
    ensemble shape (ISSUE 17): same synthetic covtype-OvR ensemble,
    three PredictServers differing ONLY in ServeConfig.union_storage,
    each reporting staged union bytes and sweep throughput. Moderate
    dual coefficients by construction (alpha_scale) so the calibrated
    guard ACCEPTS every storage — a refused leg would silently measure
    the fallback and the frontier would compare nothing; the guard's
    accept/refuse behavior itself is pinned by tests and the loadgen
    quant smoke, not here. Decision agreement across the frontier is
    checked against the f32 leg within the guard's own calibrated
    bound."""
    import warnings

    from dpsvm_tpu.serve import (PredictServer, offered_load_sweep,
                                 union_nbytes)

    sizes = [1, 2, 4, 8, 16, 32, 64, 128]
    q = np.random.default_rng(7).random((64, 54), np.float32)
    legs, dec_ref = [], None
    for storage in ("f32", "bf16", "int8"):
        m = _synthetic_multiclass(
            n_classes=7, d=54, pool=pool, sv_frac=0.4,
            strategy="ovr", gamma=0.5, seed=4, alpha_scale=1e-3)
        cfg = serve_cfg.replace(union_storage=storage,
                                metrics_port=None)
        with warnings.catch_warnings():
            warnings.simplefilter("always")
            server = PredictServer(m, cfg)
        dec = server.decision(q)
        if storage == "f32":
            dec_ref = dec
        sweep = offered_load_sweep(server, sizes, requests,
                                   group=8, seed=0)
        s_rows = int(server.ens.sv_union.shape[0])
        guard = server.stats.get("storage_guard") or {}
        leg = {
            "requested_storage": storage,
            "effective_storage": server.union_storage,
            "union_bytes": union_nbytes(server.union_storage,
                                        s_rows, server.d),
            "examples_per_second": sweep["rows_per_second"],
            "request_p50_s": sweep["request_latency"]["p50"],
            "guard_risk": (guard.get("risks") or {}).get(storage),
            "max_abs_decision_delta_vs_f32": (
                None if dec_ref is dec else
                round(float(np.max(np.abs(dec - dec_ref))), 6)),
        }
        server.close()
        assert leg["effective_storage"] == storage, leg
        legs.append(leg)
    return legs


def _scrape_metrics(server) -> dict:
    """GET the server's own /metrics endpoint and validate the
    exposition: 200, OpenMetrics-terminated (# EOF), and the
    request-latency quantiles EQUAL the shared histogram's
    percentiles() — endpoint and snapshot report one definition."""
    import urllib.request

    url = server.exporter.url
    with urllib.request.urlopen(url, timeout=10) as resp:
        status = resp.status
        text = resp.read().decode("utf-8")
    lines = text.splitlines()
    pct = server.request_seconds.percentiles()
    quantiles_ok = all(
        any(ln.startswith("serve_request_seconds{")
            and f'quantile="{q / 100:g}"' in ln
            and float(ln.rsplit(" ", 1)[-1]) == pct[f"p{q}"]
            for ln in lines)
        for q in (50, 95, 99)) if pct else False
    return {
        "url": url,
        "status": status,
        "lines": len(lines),
        "families": sum(1 for ln in lines if ln.startswith("# TYPE ")),
        "eof_terminated": bool(lines and lines[-1] == "# EOF"),
        "quantiles_match_snapshot": quantiles_ok,
        "ok": bool(status == 200 and lines and lines[-1] == "# EOF"
                   and quantiles_ok),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--pool", type=int, default=2048,
                    help="synthetic training-pool rows per shape "
                         "(default 2048 — CPU-harness friendly; raise "
                         "on a real TPU session)")
    ap.add_argument("--requests", type=int, default=256,
                    help="offered-load sweep request count")
    ap.add_argument("--query-block", type=int, default=1024)
    ap.add_argument("--obs", action="store_true",
                    help="enable the telemetry spine: serve run logs "
                         "(manifest + final histogram snapshots) for "
                         "the sweep servers; DPSVM_OBS=1 equivalent")
    ap.add_argument("--obs-dir", default=None,
                    help="run-log directory (default obs_runs; env "
                         "DPSVM_OBS_DIR)")
    args = ap.parse_args(argv)

    import jax

    import bench
    from dpsvm_tpu.config import ObsConfig, ServeConfig
    from dpsvm_tpu.serve import PredictServer, offered_load_sweep

    # metrics_port=0: every sweep server exposes /metrics on an
    # ephemeral port so the benchmark can SCRAPE ITSELF mid-sweep —
    # proving the endpoint answers (and parses) under live traffic,
    # not just on an idle server.
    serve_cfg = ServeConfig(metrics_port=0,
                            obs=ObsConfig(enabled=args.obs,
                                          runlog_dir=args.obs_dir))

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    calibration = bench._session_calibration()
    print(f"[bench_serve] device={dev} calibration={json.dumps(calibration)}",
          file=sys.stderr)

    # --- A/B at the two multiclass shapes --------------------------
    mnist_ovo = _synthetic_multiclass(
        n_classes=10, d=784, pool=args.pool, sv_frac=0.5,
        strategy="ovo", gamma=0.125, seed=3)
    covtype_ovr = _synthetic_multiclass(
        n_classes=7, d=54, pool=args.pool * 2, sv_frac=0.4,
        strategy="ovr", gamma=0.5, seed=4)
    ab = [_ab_record(mnist_ovo, args.query_block, "mnist-ovo-10c-784d"),
          _ab_record(covtype_ovr, args.query_block, "covtype-ovr-7c-54d")]
    for rec in ab:
        print(f"[bench_serve] A/B {rec['shape']}: kernel FLOPs "
              f"/{rec['kernel_flop_reduction']}, XLA FLOPs "
              f"/{rec['xla_flop_reduction']}, bit_identical="
              f"{rec['bit_identical']}", file=sys.stderr)
    assert ab[0]["kernel_flop_reduction"] >= 3.0, ab[0]
    assert all(r["bit_identical"] for r in ab), ab

    # --- union-storage frontier at matched shape (ISSUE 17) --------
    storage_ab = _storage_ab(serve_cfg, max(args.requests // 4, 64),
                             pool=max(args.pool // 2, 512))
    for leg in storage_ab:
        print(f"[bench_serve] storage {leg['requested_storage']}: "
              f"{leg['union_bytes']} union bytes, "
              f"{leg['examples_per_second']} ex/s, "
              f"|dDec|max={leg['max_abs_decision_delta_vs_f32']}",
              file=sys.stderr)
    assert storage_ab[2]["union_bytes"] * 3 < storage_ab[0]["union_bytes"], \
        storage_ab  # the ~4x union-bytes cut (int8 rows + f32 scales)

    # --- offered-load sweep through the serving engine -------------
    sizes = [1, 2, 4, 8, 16, 32, 64, 128]
    server = PredictServer(mnist_ovo, serve_cfg)
    sweep_mnist = offered_load_sweep(server, sizes, args.requests,
                                     group=8, seed=0)
    # Mid-sweep self-scrape (ISSUE 8): hit the server's own /metrics
    # endpoint while its histograms are hot and verify the exposition
    # is OpenMetrics-complete and carries the request-latency summary
    # the sweep above just reported from the SAME instruments.
    scrape = _scrape_metrics(server)
    print(f"[bench_serve] /metrics self-scrape: {scrape['url']} "
          f"ok={scrape['ok']} ({scrape['lines']} lines, "
          f"{scrape['families']} families)", file=sys.stderr)
    assert scrape["ok"], scrape
    server_cov = PredictServer(covtype_ovr, serve_cfg)
    sweep_cov = offered_load_sweep(server_cov, sizes, args.requests,
                                   group=8, seed=0)
    # Percentiles above come from the servers' SHARED obs histograms
    # (serve.request_seconds / bucket_seconds) — one definition across
    # this tool, `cli serve --server-bench` and the serve run log.
    server_cov.close()
    print(f"[bench_serve] sweep mnist-ovo: "
          f"{sweep_mnist['rows_per_second']} rows/s "
          f"p50={sweep_mnist['request_latency']['p50']}s",
          file=sys.stderr)

    result = {
        "metric": ("PredictServer offered-load sweep, synthetic "
                   "MNIST-shaped 10-class OvO (45 submodels, d=784, "
                   f"pool={args.pool}), bucketed micro-batching, "
                   "requests of 1..128 rows in groups of 8"),
        "value": sweep_mnist["rows_per_second"],
        "unit": "examples/second",
        "examples_per_second": sweep_mnist["rows_per_second"],
        "request_latency": sweep_mnist["request_latency"],
        "bucket_latency": sweep_mnist["bucket_latency"],
        "sweep_covtype_ovr": sweep_cov,
        "compacted_vs_stacked": ab,
        # Union-storage stamp (ISSUE 17): the headline sweep stages
        # the default f32 union; the regression gate refuses cross-
        # storage comparisons (STORAGE_MISMATCH) the same way it
        # refuses cross-topology ones.
        "union_storage": server.union_storage,
        "storage_frontier": storage_ab,
        "warm_seconds": {str(k): round(v, 4) for k, v in
                         server.stats["warm_seconds"].items()},
        # Device-identity stamp (ISSUE 14 satellite): the regression
        # gate refuses cross-device-kind comparisons.
        **bench._device_fields(),
        "device_numbers": ("measured" if on_tpu else
                           "pending — no TPU reachable this session; "
                           "CPU-harness wall clocks are for structure/"
                           "FLOP adjudication only (FLOP counts and "
                           "bit-parity are platform-independent)"),
        # One artifact schema across BENCH/MULTICHIP/SERVE/SMOKE
        # (dpsvm_tpu/obs/runlog.SCHEMA_VERSION via bench).
        "schema_version": bench._schema_version(),
        "session_calibration": calibration,
        # Mid-sweep /metrics self-scrape (ISSUE 8): the endpoint
        # answered under live traffic with an OpenMetrics-complete
        # exposition whose quantiles equal the snapshot's.
        "metrics_scrape": {k: scrape[k] for k in
                           ("status", "lines", "families",
                            "eof_terminated",
                            "quantiles_match_snapshot", "ok")},
    }
    if server._obs.live:
        result["runlog"] = server._obs.path
    server.close()
    gate = bench._regression_gate(result, REPO,
                                  pattern="BENCH_SERVE_r*.json",
                                  key="examples_per_second")
    result.update(gate)
    print(f"[bench_serve] regression gate: {gate.get('regression_gate')}",
          file=sys.stderr)

    nn = len(glob.glob(os.path.join(REPO, "BENCH_SERVE_r*.json"))) + 1
    art = os.path.join(REPO, f"BENCH_SERVE_r{nn:02d}.json")
    with open(art, "w") as fh:
        json.dump(result, fh, indent=1)
    print(json.dumps({k: result[k] for k in
                      ("metric", "value", "unit", "regression_gate")}))

    with open(os.path.join(REPO, "BENCH_SERVE.md"), "w") as fh:
        fh.write(
            "# BENCH_SERVE — compacted multiclass serving\n\n"
            "Command: `python tools/bench_serve.py` (artifact "
            f"`{os.path.basename(art)}`; history lives in git). "
            "Synthetic shared-SV ensembles at the MNIST-OvO and "
            "covtype-OvR shapes (tools/bench_predict.py's synthetic-SV "
            "discipline); FLOP counts are platform-independent, wall "
            "clocks on a CPU harness carry device_numbers=pending until "
            "the next TPU session re-runs this tool.\n\n"
            "## Compacted vs stacked A/B\n\n"
            "| shape | submodels | m_pad | SV union | kernel FLOPs cut "
            "| XLA FLOPs cut | bit-identical |\n"
            "|---|---|---|---|---|---|---|\n"
            + "\n".join(
                f"| {r['shape']} | {r['n_models']} | {r['m_pad']} | "
                f"{r['sv_union']} | {r['kernel_flop_reduction']}x | "
                f"{r['xla_flop_reduction']}x | {r['bit_identical']} |"
                for r in ab)
            + "\n\n## Union-storage frontier (covtype-OvR shape, "
            "matched ensemble, guard-accepted legs)\n\n"
            "| storage | union bytes | ex/s | p50 s | "
            "max |dDec| vs f32 |\n|---|---|---|---|---|\n"
            + "\n".join(
                f"| {r['effective_storage']} | {r['union_bytes']} | "
                f"{r['examples_per_second']} | {r['request_p50_s']} | "
                f"{r['max_abs_decision_delta_vs_f32']} |"
                for r in storage_ab)
            + "\n\n## Offered-load sweep (MNIST-OvO shape)\n\n```json\n"
            + json.dumps({k: result[k] for k in
                          ("value", "unit", "request_latency",
                           "bucket_latency", "device",
                           "device_numbers", "regression_gate")},
                         indent=1)
            + "\n```\n")
    print(f"[bench_serve] wrote {art} and BENCH_SERVE.md",
          file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
