"""Closed-loop load generator for the v2 serving engine (ISSUE 10).

The v1 sweep (tools/bench_serve.py offered_load_sweep) drives the
single-model PredictServer open-loop in fixed groups; it cannot express
the things serving v2 exists for — multiple registered models, hot
swaps under live traffic, deadlines. This tool drives the
:class:`dpsvm_tpu.serving.ServingEngine` CLOSED-LOOP: a fixed number of
virtual clients each keep exactly one request outstanding and resubmit
on completion, so offered load is controlled by the concurrency level
(offered rows/s = concurrency x mean request rows / service time) and
the sweep maps the latency/throughput frontier point by point.

Per sweep leg it reports throughput, p50/p95/p99 request latency and
the deadline-miss rate — all FROM THE ENGINE'S OWN SHARED HISTOGRAM
INSTRUMENTS (dpsvm_tpu/obs/metrics), scoped to the leg via the
``last=`` window discipline — never a tool-local timing aggregation.

The headline leg serves the MNIST-OvO shape of BENCH_SERVE_r01 (45
submodels, d=784 — matched so the v1 baseline is comparable) WHILE a
second registered model (covtype-OvR shape) takes a fixed share of the
traffic, and HOT-SWAPS the MNIST model to a freshly staged v2 file at
the halfway point: the acceptance contract is zero failed/dropped
requests across the swap. A separate overload leg (tight deadline,
high concurrency) demonstrates the shedding path and its explicit
deadline-miss accounting.

Writes BENCH_SERVE_r<NN>.json at the repo root (commit it) and
REWRITES BENCH_SERVE.md; the headline examples_per_second runs through
the same drift-normalized cross-session regression gate as every other
bench family (bench._regression_gate over BENCH_SERVE_r*.json).
``--smoke`` runs a short sweep for CI: same engine, same gate, runlog-
reconciled, but the artifact goes to --out (default: a temp file) so
CI runs never churn the committed history.

Run: `python tools/loadgen.py [--requests N] [--pool N] [--smoke]`.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import tempfile
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def closed_loop(engine, n_requests: int, concurrency: int, sizes,
                traffic, seed: int = 0, deadline_ms=None,
                swap_at: float = None, swap_fn=None) -> dict:
    """Drive the engine with `concurrency` virtual clients, each
    keeping one request outstanding (closed loop). `traffic` is
    [(model_name, weight), ...]; request row counts draw from `sizes`.
    `swap_fn` (if given) runs once when `swap_at` (fraction of
    requests) have completed — the mid-leg hot swap. Latency
    percentiles and the miss rate come from the ENGINE'S shared
    histograms, scoped to this leg."""
    rng = np.random.default_rng(seed)
    names = [t[0] for t in traffic]
    weights = np.asarray([t[1] for t in traffic], np.float64)
    weights /= weights.sum()
    dims = {n: engine.registry.get(n).d for n in names}
    req_sizes = rng.choice(np.asarray(sizes), n_requests)
    req_models = rng.choice(len(names), n_requests, p=weights)

    lat_base = engine.request_seconds.count
    miss_base = engine.deadline_misses.value
    exp_base = engine.expired.value
    disp_base = engine._dispatches
    occ_base = engine.batch_occupancy.count
    per_model_rows = {n: 0 for n in names}

    submitted = completed = 0
    outstanding = 0
    swapped = swap_fn is None
    verdicts = {"ok": 0, "late": 0, "expired": 0, "failed": 0}
    t0 = time.perf_counter()
    last_progress = t0
    while completed < n_requests:
        while outstanding < concurrency and submitted < n_requests:
            name = names[req_models[submitted]]
            n_rows = int(req_sizes[submitted])
            rows = rng.random((n_rows, dims[name]), dtype=np.float32)
            engine.submit(rows, model=name, deadline_ms=deadline_ms)
            per_model_rows[name] += n_rows
            submitted += 1
            outstanding += 1
        engine.pump()
        got = engine.results()
        if got:
            last_progress = time.perf_counter()
        for res in got.values():
            verdicts[res.verdict] += 1
            completed += 1
            outstanding -= 1
        if not swapped and completed >= swap_at * n_requests:
            swap_fn()
            swapped = True
        if time.perf_counter() - last_progress > 120.0:
            # Stall guard: an engine that stops completing work must
            # surface as FAILED requests in the record (the zero-loss
            # acceptance assert reads it), not hang the benchmark.
            break
    wall = time.perf_counter() - t0

    rows_total = sum(per_model_rows.values())
    lat_n = engine.request_seconds.count - lat_base
    misses = engine.deadline_misses.value - miss_base
    out = {
        "requests": int(n_requests),
        "concurrency": int(concurrency),
        "rows": int(rows_total),
        "rows_by_model": {n: int(v) for n, v in per_model_rows.items()},
        "wall_seconds": round(wall, 4),
        "rows_per_second": round(rows_total / max(wall, 1e-9)),
        "requests_per_second": round(n_requests / max(wall, 1e-9)),
        "request_latency": engine.request_seconds.percentiles(
            last=lat_n),
        "deadline_misses": int(misses),
        "expired": int(engine.expired.value - exp_base),
        "deadline_miss_rate": round(misses / max(n_requests, 1), 6),
        "verdicts": dict(verdicts),
        "dispatches": engine._dispatches - disp_base,
        "batch_occupancy": engine.batch_occupancy.percentiles(
            (50, 95), last=engine.batch_occupancy.count - occ_base),
        # Requests that never completed (stall-guard exit) — the
        # zero-loss acceptance reads this; 0 on every healthy run.
        "failed": int(n_requests - completed),
    }
    for n in names:
        h = engine._model_metrics(n)["latency"]
        if len(h):
            out.setdefault("latency_by_model", {})[n] = h.percentiles()
    return out


def _scrape(engine) -> dict:
    """Mid-sweep self-scrape of the engine's own /metrics endpoint
    (the bench_serve discipline): the exposition must be OpenMetrics-
    complete and carry the per-model serving families under traffic."""
    import urllib.request

    url = engine.exporter.url
    with urllib.request.urlopen(url, timeout=10) as resp:
        status = resp.status
        text = resp.read().decode("utf-8")
    lines = text.splitlines()
    return {
        "url": url, "status": status, "lines": len(lines),
        "families": sum(1 for ln in lines if ln.startswith("# TYPE ")),
        "eof_terminated": bool(lines and lines[-1] == "# EOF"),
        "per_model_labels": any('model="mnist"' in ln for ln in lines),
        "ok": bool(status == 200 and lines and lines[-1] == "# EOF"
                   and any('model="mnist"' in ln for ln in lines)),
    }


def _runlog_reconciliation(engine, rows_total: int) -> dict:
    """Cross-check the engine's reported rows against its OWN run log:
    the per-dispatch chunk records' pairs_delta (rows) must sum to the
    engine's row counter exactly — a dropped dispatch record or a
    double-served batch shows up as a reconciliation failure. Empty
    when obs is off."""
    if not engine._obs.live:
        return {}
    from dpsvm_tpu.obs.runlog import read_runlog, records_for

    path = engine._obs.path
    chunks = records_for(read_runlog(path), engine._obs.run_id, "chunk")
    rl_rows = sum(c.get("pairs_delta", 0) for c in chunks)
    return {
        "runlog": path,
        "runlog_chunk_records": len(chunks),
        "runlog_rows": int(rl_rows),
        "runlog_reconciles": bool(rl_rows == rows_total),
    }


# --------------------------------------------------- network front door

def _net_worker(host, port, idx, n_requests, traffic, dims, sizes,
                deadline_ms, out, reject_retries=2):
    """One closed-loop wire client: `n_requests` requests over a
    persistent connection, every outcome tallied EXPLICITLY (observed
    verdicts come from the client library's own counters, including
    rejected verdicts its retry loop swallowed) — the reconciliation's
    client side."""
    from dpsvm_tpu.serving.client import (ConnectError,
                                          ConnectionDropped,
                                          SendAborted, ServeClient,
                                          ServerDraining)

    rng = np.random.default_rng(100 + idx)
    names = [t[0] for t in traffic]
    w = np.asarray([t[1] for t in traffic], np.float64)
    w /= w.sum()
    cli = ServeClient(host, port, seed=idx, timeout_s=60.0,
                      reject_retries=reject_retries, connect_retries=3,
                      backoff_s=0.01)
    tally = {"requests": 0, "dropped": 0, "aborted_send": 0,
             "goodbyed": 0, "connect_failed": 0}
    for _ in range(n_requests):
        name = names[int(rng.choice(len(names), p=w))]
        rows = rng.random((int(rng.choice(sizes)), dims[name]),
                          dtype=np.float32)
        tally["requests"] += 1
        try:
            cli.request(rows, model=name, deadline_ms=deadline_ms)
        except SendAborted:
            tally["aborted_send"] += 1  # frame NOT fully sent
        except ConnectionDropped:
            tally["dropped"] += 1  # fully sent, verdict never read
        except ServerDraining:
            tally["goodbyed"] += 1
        except ConnectError:
            tally["connect_failed"] += 1
    tally["frames_sent"] = cli.frames_sent
    tally["observed"] = dict(cli.verdicts_observed)
    cli.close()
    out[idx] = tally


def _drain_worker(host, port, idx, traffic, dims, deadline_ms, out):
    """Sustained offered load until the server drains: loops requests
    with NO reject retry; the loop ends only on an EXPLICIT drain
    signal (a rejected-draining verdict, a GOODBYE frame, or a
    refused reconnect). Anything else — a reset without a verdict —
    lands in 'dropped'/'aborted_send' and fails the drain proof."""
    from dpsvm_tpu.serving.client import (ConnectError,
                                          ConnectionDropped,
                                          SendAborted, ServeClient,
                                          ServerDraining)

    rng = np.random.default_rng(500 + idx)
    names = [t[0] for t in traffic]
    cli = ServeClient(host, port, seed=idx, timeout_s=60.0,
                      reject_retries=0, connect_retries=2,
                      backoff_s=0.01)
    tally = {"requests": 0, "drain_rejected": 0, "goodbyed": 0,
             "connect_refused": 0, "dropped": 0, "aborted_send": 0}
    for _ in range(100_000):  # bounded: the drain ends the loop
        name = names[int(rng.integers(len(names)))]
        rows = rng.random((int(rng.integers(1, 17)), dims[name]),
                          dtype=np.float32)
        tally["requests"] += 1
        try:
            v = cli.request(rows, model=name, deadline_ms=deadline_ms)
            if v.verdict == "rejected":
                tally["drain_rejected"] += 1
                break
        except ServerDraining:
            tally["goodbyed"] += 1
            break
        except ConnectError:
            tally["connect_refused"] += 1
            break
        except ConnectionDropped:
            tally["dropped"] += 1
            break
        except SendAborted:
            tally["aborted_send"] += 1
            break
    tally["frames_sent"] = cli.frames_sent
    tally["observed"] = dict(cli.verdicts_observed)
    cli.close()
    out[idx] = tally


def _net_delta(before: dict, after: dict) -> dict:
    out = {}
    for k, v in after.items():
        if isinstance(v, dict):
            out[k] = {kk: v[kk] - before[k].get(kk, 0) for kk in v}
        elif isinstance(v, int):
            out[k] = v - before.get(k, 0)
    return out


def _reconcile_net(delta: dict, tallies: list, leg: str,
                   clean: bool) -> dict:
    """The conservation law, asserted EXACTLY: every frame the clients
    fully sent was accepted; every accepted frame got exactly one
    verdict; every verdict was observed by its client unless that
    client provably abandoned the connection (dropped) or was drained
    past a GOODBYE."""
    from dpsvm_tpu.serving import wire

    observed = {v: sum(t["observed"][v] for t in tallies)
                for v in wire.VERDICTS}
    sent = sum(t["frames_sent"] for t in tallies)
    dropped = sum(t["dropped"] for t in tallies)
    goodbyed = sum(t.get("goodbyed", 0) for t in tallies)
    acc = delta["frames_accepted"]
    checks = {
        "server_conservation":
            acc == sum(delta["verdicts"].values()),
    }
    if leg != "drain":
        # Outside a drain every fully-sent frame is provably accepted
        # and every accepted frame's verdict is either observed or
        # belongs to a connection the client itself abandoned — both
        # equalities are EXACT.
        checks["frames_sent_match"] = sent == acc
        checks["every_frame_accounted"] = (
            sum(observed.values()) + dropped + goodbyed == acc)
    else:
        # During a drain two narrow races (a frame sent into a socket
        # whose reader already exited; a GOODBYE surfacing mid-send)
        # make sent/goodbyed upper bounds rather than equalities; the
        # exact laws that DO survive a drain:
        checks["frames_sent_bound"] = sent >= acc
        # every delivered verdict was observed (no client abandoned a
        # socket during drain)
        checks["delivered_all_observed"] = (
            sum(observed.values())
            == sum(delta["verdicts"].values())
            - delta["undeliverable_total"])
    if clean:
        checks["per_class_exact"] = observed == delta["verdicts"]
        checks["zero_undeliverable"] = \
            delta["undeliverable_total"] == 0
    rec = {"leg": leg, "frames_sent": sent, "frames_accepted": acc,
           "client_observed": observed,
           "server_verdicts": delta["verdicts"],
           "undeliverable": delta["undeliverable_total"],
           "dropped": dropped, "goodbyed": goodbyed,
           "checks": checks}
    assert all(checks.values()), rec
    return rec


def _fuzz_burst(host, port, seed: int = 0) -> dict:
    """Seeded protocol fuzz against a LIVE server: wrong magic,
    hostile length prefix, truncated payload, garbage bytes, mid-frame
    disconnect — each must cost exactly its own connection (ERROR
    frame or a counted abort), never a wedge (ISSUE 15 satellite;
    tests/test_serve_net.py runs the same generator in-suite)."""
    import socket as socketlib
    import struct

    from dpsvm_tpu.serving import wire

    rng = np.random.default_rng(seed)
    sent = {"protocol": 0, "aborted": 0}
    for i in range(12):
        case = i % 4
        sock = socketlib.create_connection((host, port), timeout=10)
        try:
            if case == 0:  # wrong magic
                sock.sendall(b"XX" + bytes(rng.integers(
                    0, 256, 14, dtype=np.uint8)))
                sent["protocol"] += 1
            elif case == 1:  # hostile length prefix
                sock.sendall(struct.pack("!2sBBI", b"DS", 1,
                                         wire.T_REQUEST, 1 << 31))
                sent["protocol"] += 1
            elif case == 2:  # truncated payload, mid-frame disconnect
                sock.sendall(struct.pack("!2sBBI", b"DS", 1,
                                         wire.T_REQUEST, 100)
                             + b"\x00" * 10)
                sent["aborted"] += 1
            else:  # garbage that cannot be a header
                junk = bytes(rng.integers(0, 256, 32, dtype=np.uint8))
                sock.sendall(b"\x00\x00" + junk)
                sent["protocol"] += 1
            if case != 2:
                sock.settimeout(10)
                try:  # the ERROR frame (or clean close) must arrive
                    sock.recv(4096)
                except OSError:
                    pass
        finally:
            sock.close()
    return sent


def _run_net(args, engine, paths, tmp, journal_path, sizes,
             traffic) -> int:
    """``loadgen --net``: the ISSUE 15 acceptance run. Clean leg with
    per-class EXACT client/server verdict reconciliation; seeded
    chaos leg (connection kills, a stalled reader, partial writes, an
    accept drop, one mid-leg hot swap); protocol fuzz burst; graceful
    drain under sustained offered load; journal rehydrate with
    BITWISE-identical decisions re-proven through the socket path."""
    import threading

    import bench
    from dpsvm_tpu.config import ObsConfig, ServeConfig
    from dpsvm_tpu.serving import ServeServer, ServingEngine
    from dpsvm_tpu.serving.client import ServeClient
    from dpsvm_tpu.testing import faults as fault_harness

    server = ServeServer(engine)
    print(f"[loadgen] front door on {server.host}:{server.port}",
          file=sys.stderr)
    names = [t[0] for t in traffic]
    dims = {n: engine.registry.get(n).d for n in names}
    n_clients = 4 if args.smoke else 8
    per_client = max(6, args.requests // n_clients)

    def run_leg(tag, n_req, reject_retries=2):
        before = server.net_snapshot()
        out = [None] * n_clients
        threads = [threading.Thread(
            target=_net_worker,
            args=(server.host, server.port, i, n_req, traffic, dims,
                  sizes, args.deadline_ms, out, reject_retries),
            name=f"loadgen-net-{tag}-{i}") for i in range(n_clients)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        return before, out, threads, t0

    # --- clean leg: per-class EXACT reconciliation.
    before, out, threads, t0 = run_leg("clean", per_client)
    for t in threads:
        t.join(timeout=300)
        assert not t.is_alive(), "clean-leg client wedged"
    wall = time.perf_counter() - t0
    clean = _reconcile_net(_net_delta(before, server.net_snapshot()),
                           out, "clean", clean=True)
    clean["wall_seconds"] = round(wall, 3)
    clean["rows_per_second"] = None  # rows ride the engine counters
    print(f"[loadgen] net clean leg: {clean['frames_accepted']} "
          f"frames, verdicts {clean['server_verdicts']}, reconciled "
          "EXACTLY", file=sys.stderr)

    # --- chaos leg: seeded connection faults + one mid-leg hot swap.
    fault_harness.NET_STALL_SECONDS = 0.4
    plan = fault_harness.FaultPlan.parse(
        "net_conn_drop@5x2,net_read_stall@9,net_partial_write@13,"
        "net_accept@3", seed=7)
    swap_done = {}

    def _swap():
        time.sleep(0.3)  # mid-leg: traffic provably in flight
        entry = engine.swap("mnist", paths["mnist_v2"])
        swap_done["version"] = entry.version

    swap_th = threading.Thread(target=_swap, name="loadgen-net-swap")
    with fault_harness.install(plan):
        before, out, threads, t0 = run_leg("chaos", per_client)
        swap_th.start()
        for t in threads:
            t.join(timeout=300)
            assert not t.is_alive(), "chaos-leg client wedged"
        swap_th.join(timeout=120)
    assert not swap_th.is_alive(), "mid-leg hot swap never finished"
    delta = _net_delta(before, server.net_snapshot())
    chaos = _reconcile_net(delta, out, "chaos", clean=False)
    chaos["faults_fired"] = dict(plan.fired)
    chaos["hot_swap_to_version"] = swap_done.get("version")
    assert plan.fired["net_conn_drop"] == 2, plan.fired
    assert plan.fired["net_partial_write"] == 1, plan.fired
    assert plan.fired["net_read_stall"] == 1, plan.fired
    assert plan.fired["net_accept"] == 1, plan.fired
    assert chaos["dropped"] == 2, chaos  # the two killed connections
    assert sum(t["aborted_send"] for t in out) == 1
    assert delta["verdicts"]["failed"] == 0, delta  # drops never fail
    assert swap_done.get("version") == 2
    print(f"[loadgen] net chaos leg: fired {dict(plan.fired)}, "
          f"swap -> v{swap_done['version']}, accounting closed "
          f"({chaos['frames_accepted']} frames, {chaos['dropped']} "
          "dropped, 0 unaccounted)", file=sys.stderr)

    # --- protocol fuzz burst (the satellite's seeded generator).
    before_fuzz = server.net_snapshot()
    fuzz_sent = _fuzz_burst(server.host, server.port, seed=11)
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        dfz = _net_delta(before_fuzz, server.net_snapshot())
        if (dfz["protocol_errors"] == fuzz_sent["protocol"]
                and dfz["conns_aborted"] == fuzz_sent["aborted"]
                and dfz["conns_opened"] == dfz["conns_closed"]):
            break
        time.sleep(0.02)
    assert dfz["protocol_errors"] == fuzz_sent["protocol"], (dfz,
                                                             fuzz_sent)
    assert dfz["conns_aborted"] == fuzz_sent["aborted"], (dfz,
                                                          fuzz_sent)
    assert dfz["frames_accepted"] == 0, dfz
    # …and the server still serves cleanly after the abuse.
    probe_cli = ServeClient(server.host, server.port, seed=99)
    rng = np.random.default_rng(123)
    probes = {n: rng.random((8, dims[n]), dtype=np.float32)
              for n in names}
    pre = {n: probe_cli.decision(probes[n], model=n) for n in names}
    probe_cli.close()
    print(f"[loadgen] net fuzz burst: {fuzz_sent} -> counters "
          "reconciled, server healthy", file=sys.stderr)

    # --- /metrics carries the front-door families (one scrape, one
    # truth — the reconciliation above could have been done FROM a
    # scrape).
    scrape = _scrape(engine)
    assert scrape["ok"], scrape
    import urllib.request
    with urllib.request.urlopen(engine.exporter.url, timeout=10) as r:
        text = r.read().decode()
    for fam in ("serving_net_frames_accepted",
                "serving_net_protocol_errors",
                'serving_net_verdicts_total{verdict="rejected"}'):
        assert fam in text, fam

    # --- graceful drain under sustained offered load.
    before_drain = server.net_snapshot()
    out_d = [None] * n_clients
    threads = [threading.Thread(
        target=_drain_worker,
        args=(server.host, server.port, i, traffic, dims,
              args.deadline_ms, out_d),
        name=f"loadgen-net-drain-{i}") for i in range(n_clients)]
    for t in threads:
        t.start()
    time.sleep(0.6)  # offered load provably sustained
    drain_snap = server.drain()
    for t in threads:
        t.join(timeout=120)
        assert not t.is_alive(), "drain-leg client wedged"
    drain = _reconcile_net(_net_delta(before_drain, drain_snap),
                           out_d, "drain", clean=False)
    # THE DRAIN PROOF: every client loop ended on an EXPLICIT signal.
    assert drain["dropped"] == 0, out_d
    assert sum(t["aborted_send"] for t in out_d) == 0, out_d
    ended = {k: sum(t[k] for t in out_d)
             for k in ("drain_rejected", "goodbyed", "connect_refused")}
    assert sum(ended.values()) == n_clients, (ended, out_d)
    drain["ended_by"] = ended
    print(f"[loadgen] net drain under load: {drain['frames_accepted']}"
          f" frames during drain window, clients ended by {ended}, "
          "zero resets without a verdict", file=sys.stderr)

    # --- rehydrate proof through the socket path: a NEW engine on the
    # same journal (the drained one is deliberately NOT closed first)
    # must serve BITWISE-identical decisions over the wire.
    eng2 = ServingEngine(ServeConfig(
        deadline_ms=args.deadline_ms, journal_path=journal_path,
        obs=ObsConfig(enabled=args.obs, runlog_dir=args.obs_dir)))
    srv2 = ServeServer(eng2)
    cli2 = ServeClient(srv2.host, srv2.port, seed=7)
    rehydrated_versions = {e.name: e.version
                           for e in eng2.registry.entries()}
    bitwise = {}
    for n in names:
        post = cli2.decision(probes[n], model=n)
        bitwise[n] = bool(np.array_equal(pre[n], post))
    cli2.close()
    assert all(bitwise.values()), bitwise
    assert rehydrated_versions.get("mnist") == 2, rehydrated_versions
    srv2.close()
    eng2.close()
    print(f"[loadgen] net rehydrate: versions {rehydrated_versions}, "
          "socket-path decisions BITWISE identical", file=sys.stderr)

    runlog_rec = _net_runlog_reconciliation(engine, drain_snap)
    result = {
        "metric": ("network front door (ISSUE 15): wire-level serving "
                   "over the v2 engine — clean/chaos/fuzz/drain legs "
                   f"with {n_clients} persistent-connection clients, "
                   "seeded connection faults, one mid-leg hot swap, "
                   "graceful drain under load, journal rehydrate "
                   "re-proven bitwise through the socket path"),
        "listen": f"{server.host}:{server.port}",
        "clients": n_clients,
        "legs": {"clean": clean, "chaos": chaos, "drain": drain},
        "fuzz": {**fuzz_sent, "counters": dfz},
        "rehydrate": {"versions": rehydrated_versions,
                      "decisions_bitwise": bitwise},
        "server_final": drain_snap,
        "metrics_scrape": {k: scrape[k] for k in
                           ("status", "lines", "families", "ok")},
        **runlog_rec,
        **bench._device_fields(),
        "schema_version": bench._schema_version(),
        "smoke": bool(args.smoke),
    }
    engine.close()

    # Zero server-thread leaks after drain (the acceptance criterion).
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        leaked = [t.name for t in threading.enumerate()
                  if t.name.startswith("dpsvm-net")]
        if not leaked:
            break
        time.sleep(0.05)
    assert not leaked, f"leaked server threads: {leaked}"
    result["thread_leaks"] = 0

    art = args.out or os.path.join(tmp, "BENCH_SERVE_NET_smoke.json"
                                   if args.smoke else
                                   "BENCH_SERVE_NET.json")
    with open(art, "w") as fh:
        json.dump(result, fh, indent=1)
    print(json.dumps({"metric": "serve_net", "frames": {
        leg: result["legs"][leg]["frames_accepted"]
        for leg in result["legs"]}, "reconciled": True,
        "thread_leaks": 0}))
    print(f"[loadgen] wrote {art}", file=sys.stderr)
    return 0


#: Device-time floor for the replica scale-out legs, in µs per PADDED
#: row (ServeConfig.device_floor_us_per_row). On the 1-core CI harness
#: both legs of a replica comparison would otherwise contend for the
#: same core and measure nothing but GIL arithmetic; the floor gives
#: every engine a serial emulated accelerator timeline (sleep-based,
#: GIL released) so dispatch is device-latency-bound — the TPU serving
#: regime — and the frontier measures the front door's ROUTING AND
#: OVERLAP of replica device timelines. A serialization bug still
#: shows ~1x. The same floor applies to every leg and is stamped into
#: the artifact under ``device_emulation``.
REPLICA_FLOOR_US = 250.0


def _run_replicas(args, paths, tmp, sizes, traffic) -> int:
    """``loadgen --net --replicas N`` (ISSUE 16): the horizontal
    scale-out frontier. One fleet per leg at r = 1..N replicas behind
    one front door, identical workload and device-time floor, exact
    client/server verdict reconciliation per leg; then a chaos leg at
    r = N (seeded connection faults + one mid-leg FLEET-WIDE hot swap
    — post-swap every replica must serve the new version). Headline:
    aggregate served examples/s at r = N, with the r=1 leg as the
    in-artifact scaling baseline."""
    import threading

    import bench
    from dpsvm_tpu.config import ObsConfig, ServeConfig
    from dpsvm_tpu.serving import ReplicaFleet, ServeServer
    from dpsvm_tpu.testing import faults as fault_harness

    calibration = bench._session_calibration()
    names = [t[0] for t in traffic]
    n_clients = 4 if args.smoke else 8
    per_client = max(6, args.requests // n_clients)
    floor = REPLICA_FLOOR_US

    def fleet_leg(r, tag, n_req, plan=None, swap_mid=False):
        """One measured leg: fresh fleet of r replicas, fresh journal,
        closed-loop wire clients, exact reconciliation. Returns the
        leg record (rates from the FLEET'S OWN row counters over the
        client wall window, never a tool-local sum)."""
        journal = os.path.join(tmp, f"registry_{tag}.journal")
        cfg = ServeConfig(
            listen="127.0.0.1:0", replicas=r,
            device_floor_us_per_row=floor, deadline_ms=None,
            journal_path=journal,
            obs=ObsConfig(enabled=args.obs, runlog_dir=args.obs_dir))
        fleet = ReplicaFleet(cfg)
        server = ServeServer(fleet)
        fleet.register("mnist", paths["mnist_v1"])
        fleet.register("aux", paths["aux"])
        dims = {n: fleet.engines[0].registry.get(n).d for n in names}
        before = server.net_snapshot()
        rows_before = fleet.snapshot()["rows"]
        out = [None] * n_clients
        threads = [threading.Thread(
            target=_net_worker,
            args=(server.host, server.port, i, n_req, traffic, dims,
                  sizes, None, out),
            name=f"loadgen-rep-{tag}-{i}") for i in range(n_clients)]
        swap_done = {}
        swap_th = None
        if swap_mid:
            def _swap():
                time.sleep(0.4)  # mid-leg: traffic provably in flight
                entry = fleet.swap("mnist", paths["mnist_v2"])
                swap_done["version"] = entry.version

            swap_th = threading.Thread(target=_swap,
                                       name=f"loadgen-rep-swap-{tag}")
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        if swap_th is not None:
            swap_th.start()
        for t in threads:
            t.join(timeout=600)
            assert not t.is_alive(), f"{tag} client wedged"
        wall = time.perf_counter() - t0
        if swap_th is not None:
            swap_th.join(timeout=120)
            assert not swap_th.is_alive(), "mid-leg fleet swap wedged"
            # Cross-replica swap consistency: EVERY replica now serves
            # the new version (the shared-journal lockstep contract).
            vers = [eng.registry.get("mnist").version
                    for eng in fleet.engines]
            assert vers == [swap_done["version"]] * r, vers
        rows = fleet.snapshot()["rows"] - rows_before
        rec = _reconcile_net(_net_delta(before, server.net_snapshot()),
                             out, tag, clean=(plan is None))
        per_rep = server.replica_snapshot()
        if r > 1:
            # Near-linear needs every replica pulling: a routing bug
            # that parks a replica shows up here, not just as a slow
            # aggregate.
            assert all(s["verdicts"]["served"] > 0 for s in per_rep), \
                per_rep
        server.close()
        fleet.close()
        leg = {
            "replicas": r, "clients": n_clients,
            "requests": n_clients * n_req,
            "rows_served": int(rows),
            "wall_seconds": round(wall, 3),
            "examples_per_second": round(rows / wall, 1),
            "reconciliation": rec,
            "per_replica_served": [s["verdicts"]["served"]
                                   for s in per_rep],
            **({"hot_swap_to_version": swap_done.get("version")}
               if swap_mid else {}),
        }
        print(f"[loadgen] replicas={r} ({tag}): "
              f"{leg['examples_per_second']} ex/s aggregate "
              f"({leg['rows_served']} rows / {leg['wall_seconds']}s), "
              f"per-replica served {leg['per_replica_served']}",
              file=sys.stderr)
        return leg

    # --- the scale-out frontier: r = 1..N, identical workload+floor.
    frontier = [fleet_leg(r, f"clean_r{r}", per_client)
                for r in range(1, args.replicas + 1)]
    base = frontier[0]["examples_per_second"]
    peak = frontier[-1]["examples_per_second"]
    speedup = peak / base
    print(f"[loadgen] scale-out frontier: "
          + " -> ".join(f"r{lg['replicas']}="
                        f"{lg['examples_per_second']}"
                        for lg in frontier)
          + f" ({speedup:.2f}x at r={args.replicas})",
          file=sys.stderr)
    floor_bound = speedup >= (1.2 if args.smoke else 1.6)
    assert floor_bound, (
        f"replica scale-out {speedup:.2f}x below bound — the front "
        f"door is serializing replicas: {frontier}")

    # --- chaos mini-leg at r = N: seeded connection faults + one
    # mid-leg fleet-wide hot swap, accounting closed exactly.
    fault_harness.NET_STALL_SECONDS = 0.4
    plan = fault_harness.FaultPlan.parse(
        "net_conn_drop@5,net_accept@3", seed=17)
    with fault_harness.install(plan):
        chaos = fleet_leg(args.replicas, f"chaos_r{args.replicas}",
                          per_client, plan=plan, swap_mid=True)
    chaos["faults_fired"] = dict(plan.fired)
    assert plan.fired["net_conn_drop"] == 1, plan.fired
    assert chaos["reconciliation"]["dropped"] == 1, chaos
    assert chaos["hot_swap_to_version"] == 2, chaos

    result = {
        "metric": ("replica fleet scale-out (ISSUE 16): aggregate "
                   "closed-loop served examples/s through ONE network "
                   f"front door at 1..{args.replicas} engine replicas, "
                   "identical workload and per-replica device-time "
                   "floor; chaos leg with seeded connection faults "
                   "and a mid-leg fleet-wide hot swap"),
        "value": peak,
        "unit": "examples/second",
        "examples_per_second": peak,
        "baseline_1_replica_examples_per_second": base,
        "scaleout_speedup": round(speedup, 3),
        "frontier": frontier,
        "chaos_leg": chaos,
        # Topology stamps (ISSUE 16 satellite): the regression gate
        # refuses cross-topology comparisons on these.
        "replicas": args.replicas,
        "union_mesh_devices": 1,
        # Transparency stamp: these are DEVICE-EMULATED numbers. The
        # floor makes dispatch device-latency-bound on the 1-core CI
        # harness so the frontier measures front-door scale-out;
        # host-bound absolute throughput is the standard loadgen run.
        "device_emulation": {
            "device_floor_us_per_row": floor,
            "charged_per": "padded row, serial per engine",
            "reason": ("single-core CI harness: without an emulated "
                       "device timeline both replicas contend for "
                       "one core and the comparison measures "
                       "nothing"),
        },
        **bench._device_fields(),
        "device_numbers": ("pending — device-emulated CPU-harness "
                          "run; a TPU session re-runs this sweep "
                          "with real accelerator timelines"),
        "schema_version": bench._schema_version(),
        "session_calibration": calibration,
        "smoke": bool(args.smoke),
    }

    gate = bench._regression_gate(result, REPO,
                                  pattern="BENCH_SERVE_r*.json",
                                  key="examples_per_second")
    result.update(gate)
    print(f"[loadgen] regression gate: {gate.get('regression_gate')} "
          "(cross-topology runs refuse by design; same-topology "
          "replica artifacts adjudicate normally)", file=sys.stderr)

    if args.out:
        art = args.out
    elif args.smoke:
        art = os.path.join(tmp, "BENCH_SERVE_replicas_smoke.json")
    else:
        nn = len(glob.glob(os.path.join(REPO,
                                        "BENCH_SERVE_r*.json"))) + 1
        art = os.path.join(REPO, f"BENCH_SERVE_r{nn:02d}.json")
    with open(art, "w") as fh:
        json.dump(result, fh, indent=1)
    print(json.dumps({k: result[k] for k in
                      ("metric", "value", "unit", "scaleout_speedup",
                       "regression_gate")}))
    print(f"[loadgen] wrote {art}", file=sys.stderr)
    return 0


def _net_runlog_reconciliation(engine, snap: dict) -> dict:
    """Runlog side of the accounting: the serve run log's conn/drain
    event records must agree with the server counters (empty when obs
    is off)."""
    if not engine._obs.live:
        return {}
    from dpsvm_tpu.obs.runlog import read_runlog, records_for

    events = records_for(read_runlog(engine._obs.path),
                         engine._obs.run_id, "event")
    n_open = sum(1 for e in events if e.get("name") == "conn_open")
    n_close = sum(1 for e in events if e.get("name") == "conn_close")
    n_drain = sum(1 for e in events if e.get("name") == "drain")
    ok = (n_open == snap["conns_opened"]
          and n_close == snap["conns_closed"] and n_drain == 2)
    rec = {"runlog": engine._obs.path,
           "runlog_conn_open": n_open, "runlog_conn_close": n_close,
           "runlog_drain_events": n_drain,
           "runlog_net_reconciles": bool(ok)}
    assert ok, rec
    return rec


def _run_quant_smoke(args) -> int:
    """``loadgen --quant-smoke`` (ISSUE 17 CI leg). Three phases:

    1. ACCEPT — a moderate-coefficient ensemble requested at
       ``union_storage='int8'`` must stage int8 (guard risk under the
       threshold), visible in the engine snapshot and the quantized-
       unions gauge, and carry traffic with zero failures.
    2. REFUSE — a large-coefficient ensemble requested at int8 must
       be REFUSED by the calibrated guard (loud UserWarning, effective
       storage falls back to a bound-accepted wider dtype) and the
       fallback must keep serving cleanly — a refusal is a safe
       downgrade, never an outage.
    3. FRONTIER — an f32-vs-int8 mini-sweep at matched shape driven
       through the WIRE front door (ServeServer + persistent-
       connection clients), client verdicts reconciled, per-leg
       union storage asserted from the engine's own snapshot.
    """
    import tempfile
    import threading
    import warnings

    from dpsvm_tpu.config import ServeConfig
    from dpsvm_tpu.serving import ServeServer, ServingEngine
    from tools.bench_serve import _synthetic_multiclass

    tmp = tempfile.mkdtemp(prefix="dpsvm_quant_smoke_")
    pool = min(args.pool, 512)
    sizes = [1, 4, 16, 64]
    moderate = os.path.join(tmp, "moderate.npz")
    _synthetic_multiclass(7, 54, pool, 0.4, "ovr", 0.5, seed=4,
                          alpha_scale=1e-3).save(moderate)
    risky = os.path.join(tmp, "risky.npz")
    _synthetic_multiclass(7, 54, pool, 0.4, "ovr", 0.5, seed=5,
                          alpha_scale=50.0).save(risky)

    # --- 1. accept leg -------------------------------------------
    eng = ServingEngine(ServeConfig(union_storage="int8"))
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        eng.register("q", moderate)
        accept_warned = [str(w.message) for w in caught
                         if "int8" in str(w.message)]
    snap = eng.snapshot()
    assert snap["union_storage"]["q"] == "int8", snap["union_storage"]
    assert snap["quantized_unions"] >= 1, snap
    assert not accept_warned, accept_warned
    accept = closed_loop(eng, 48, 4, sizes, [("q", 1.0)], seed=0)
    assert accept["failed"] == 0 \
        and accept["verdicts"]["failed"] == 0, accept
    accept_bytes = eng.snapshot()["union_bytes"]
    eng.close()
    print(f"[loadgen] quant accept leg: staged int8 "
          f"({accept_bytes} union bytes), "
          f"{accept['rows_per_second']} rows/s, zero failures",
          file=sys.stderr)

    # --- 2. refuse leg -------------------------------------------
    eng = ServingEngine(ServeConfig(union_storage="int8"))
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        eng.register("q", risky)
        refusals = [str(w.message) for w in caught
                    if "REFUSED" in str(w.message)]
    assert refusals, "risky int8 request was not refused"
    fallback = eng.snapshot()["union_storage"]["q"]
    assert fallback != "int8", fallback
    refuse = closed_loop(eng, 48, 4, sizes, [("q", 1.0)], seed=1)
    assert refuse["failed"] == 0 \
        and refuse["verdicts"]["failed"] == 0, refuse
    eng.close()
    print(f"[loadgen] quant refuse leg: int8 REFUSED, fell back to "
          f"{fallback}, fallback served "
          f"{refuse['rows_per_second']} rows/s cleanly",
          file=sys.stderr)

    # --- 3. wire-front-door frontier mini-sweep ------------------
    frontier = []
    for storage in ("f32", "int8"):
        eng = ServingEngine(ServeConfig(union_storage=storage))
        eng.register("q", moderate)
        server = ServeServer(eng)
        dims = {"q": eng.registry.get("q").d}
        n_clients, per_client = 2, 24
        out = [None] * n_clients
        rows_base = eng._rows_total
        threads = [threading.Thread(
            target=_net_worker,
            args=(server.host, server.port, i, per_client,
                  [("q", 1.0)], dims, sizes, None, out),
            name=f"quant-net-{storage}-{i}")
            for i in range(n_clients)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
            assert not t.is_alive(), f"{storage} wire client wedged"
        wall = time.perf_counter() - t0
        rows = eng._rows_total - rows_base
        ok = sum(t_["observed"].get("served", 0) for t_ in out if t_)
        snap = eng.snapshot()
        leg = {
            "union_storage": snap["union_storage"]["q"],
            "union_bytes": snap["union_bytes"],
            "quantized_unions": snap["quantized_unions"],
            "rows": int(rows),
            "rows_per_second": round(rows / max(wall, 1e-9)),
            "client_ok_verdicts": int(ok),
            "requests": n_clients * per_client,
        }
        assert leg["union_storage"] == storage, leg
        assert leg["client_ok_verdicts"] == leg["requests"], leg
        server.close()
        eng.close()
        frontier.append(leg)
        print(f"[loadgen] quant wire leg {storage}: "
              f"{leg['union_bytes']} union bytes, "
              f"{leg['rows_per_second']} rows/s, "
              f"{leg['client_ok_verdicts']}/{leg['requests']} ok",
              file=sys.stderr)
    assert frontier[1]["union_bytes"] * 3 < frontier[0]["union_bytes"], \
        frontier

    result = {
        "quant_smoke": {
            "accept_leg": {"union_bytes": accept_bytes,
                           **{k: accept[k] for k in
                              ("rows_per_second", "verdicts",
                               "failed")}},
            "refuse_leg": {"fallback_storage": fallback,
                           "refusal_warning": refusals[0][:200],
                           **{k: refuse[k] for k in
                              ("rows_per_second", "verdicts",
                               "failed")}},
            "wire_frontier": frontier,
        },
        "pool": pool,
        "smoke": True,
    }
    art = args.out or os.path.join(tmp, "BENCH_SERVE_quant_smoke.json")
    with open(art, "w") as fh:
        json.dump(result, fh, indent=1)
    print(f"[loadgen] quant smoke PASSED; wrote {art}")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--pool", type=int, default=2048,
                    help="synthetic training-pool rows (matched to "
                         "BENCH_SERVE_r01's default)")
    ap.add_argument("--requests", type=int, default=512,
                    help="requests per sweep leg")
    ap.add_argument("--concurrency", default="4,16,64",
                    help="comma list of closed-loop client counts "
                         "(the offered-load control)")
    ap.add_argument("--deadline-ms", type=float, default=250.0,
                    help="per-request deadline for the sweep legs "
                         "(generous on purpose — the overload leg "
                         "tightens it)")
    ap.add_argument("--aux-share", type=float, default=0.15,
                    help="traffic share of the second registered model")
    ap.add_argument("--net", action="store_true",
                    help="drive the engine through the NETWORK FRONT "
                         "DOOR (ISSUE 15) instead of in-process: a "
                         "real localhost socket, persistent-"
                         "connection wire clients, a seeded chaos "
                         "leg (connection kills, a stalled reader, "
                         "partial writes, an accept drop, one "
                         "mid-leg hot swap), a protocol fuzz burst, "
                         "a graceful drain under sustained load, and "
                         "a journal rehydrate re-proven BITWISE "
                         "through the socket path — client-observed "
                         "verdict counts reconciled EXACTLY against "
                         "server counters and the runlog")
    ap.add_argument("--replicas", type=int, default=1,
                    help="with --net: run the ISSUE 16 horizontal "
                         "scale-out sweep instead — one ReplicaFleet "
                         "per leg at 1..N engine replicas behind one "
                         "front door, identical workload and "
                         "per-replica device-time floor "
                         f"({'%g' % 250.0}us/padded row, stamped as "
                         "device_emulation), aggregate served "
                         "examples/s reconciled exactly per leg, "
                         "plus a chaos leg with connection faults "
                         "and a mid-leg fleet-wide hot swap")
    ap.add_argument("--chaos", action="store_true",
                    help="run the CHAOS leg after the sweep (ISSUE "
                         "13): a corrupted-file hot swap at the best "
                         "operating point (must be refused, live "
                         "version keeps serving) and an engine "
                         "kill/rehydrate-from-journal cycle "
                         "(decisions must be identical per live "
                         "model version, zero failed/expired on the "
                         "surviving path); always on with --smoke")
    ap.add_argument("--smoke", action="store_true",
                    help="short CI sweep: fewer requests, artifact to "
                         "--out (never the committed r<NN> series), no "
                         "BENCH_SERVE.md rewrite; the gate and runlog "
                         "reconciliation still run")
    ap.add_argument("--quant-smoke", action="store_true",
                    help="ISSUE 17 CI leg: the int8 storage guard's "
                         "accept AND refuse behavior on real engines "
                         "(moderate-coef model staged int8; risky-"
                         "coef model refused int8 with the fallback "
                         "still serving), plus an f32-vs-int8 "
                         "frontier mini-sweep driven through the "
                         "wire front door; artifact to --out or a "
                         "temp file, never the committed series")
    ap.add_argument("--out", default=None,
                    help="artifact path override (default: repo-root "
                         "BENCH_SERVE_r<NN>.json, or a temp file with "
                         "--smoke)")
    ap.add_argument("--obs", action="store_true",
                    help="enable the serve run log (chunk record per "
                         "dispatch; reconciled against the reported "
                         "row totals)")
    ap.add_argument("--obs-dir", default=None)
    args = ap.parse_args(argv)
    if args.quant_smoke:
        return _run_quant_smoke(args)
    if args.smoke:
        args.pool = min(args.pool, 512)
        args.requests = min(args.requests, 96)
        args.concurrency = "4,16"
    if args.replicas > 1:
        if not args.net:
            print("error: --replicas requires --net (the replica "
                  "fleet lives behind the network front door)",
                  file=sys.stderr)
            return 2
        # The replica sweep is DEVICE-floor-bound by design; a small
        # pool keeps the host-side matmuls far under the emulated
        # device time so the frontier measures routing, not the one
        # CI core (stamped in the artifact as device_emulation).
        args.pool = min(args.pool, 512)

    import jax

    import bench
    from dpsvm_tpu.config import ObsConfig, ServeConfig
    from dpsvm_tpu.serving import ServingEngine
    from tools.bench_serve import _synthetic_multiclass

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    calibration = bench._session_calibration()
    print(f"[loadgen] device={dev} calibration={json.dumps(calibration)}",
          file=sys.stderr)

    # --- models: the r01-matched MNIST-OvO shape, a second covtype-OvR
    # model, and a v2 MNIST file for the mid-sweep hot swap (freshly
    # sampled SVs -> a different union, the realistic retrain case).
    tmp = tempfile.mkdtemp(prefix="dpsvm_loadgen_")
    mnist_v1 = _synthetic_multiclass(10, 784, args.pool, 0.5, "ovo",
                                     0.125, seed=3)
    mnist_v2 = _synthetic_multiclass(10, 784, args.pool, 0.5, "ovo",
                                     0.125, seed=13)
    aux = _synthetic_multiclass(7, 54, args.pool * 2, 0.4, "ovr",
                                0.5, seed=4)
    paths = {}
    for name, m in (("mnist_v1", mnist_v1), ("mnist_v2", mnist_v2),
                    ("aux", aux)):
        paths[name] = os.path.join(tmp, f"{name}.npz")
        m.save(paths[name])

    sizes = [1, 2, 4, 8, 16, 32, 64, 128]
    traffic = [("mnist", 1.0 - args.aux_share), ("aux", args.aux_share)]

    if args.replicas > 1:
        # The ISSUE 16 scale-out sweep builds one fleet per leg from
        # the shared model files; the single-engine paths below never
        # run.
        return _run_replicas(args, paths, tmp, sizes, traffic)

    # The registry journal rides along from the start (free: one tiny
    # atomic JSON rewrite per register/swap) — it is what the chaos
    # leg's kill/rehydrate cycle replays.
    journal_path = os.path.join(tmp, "registry.journal")
    config = ServeConfig(metrics_port=0,
                         deadline_ms=args.deadline_ms,
                         journal_path=journal_path,
                         obs=ObsConfig(enabled=args.obs,
                                       runlog_dir=args.obs_dir))
    engine = ServingEngine(config)
    t0 = time.perf_counter()
    engine.register("mnist", paths["mnist_v1"])
    engine.register("aux", paths["aux"])
    print(f"[loadgen] registered 2 models in "
          f"{time.perf_counter() - t0:.2f}s", file=sys.stderr)

    levels = [int(t) for t in args.concurrency.split(",") if t]

    if args.net:
        # The ISSUE 15 acceptance run: the same engine, models and
        # journal, but every request crosses a real localhost socket.
        return _run_net(args, engine, paths, tmp, journal_path, sizes,
                        traffic)

    # --- clean frontier sweep first: the latency/throughput frontier
    # point by point, including levels past the saturation knee (where
    # the CPU harness legitimately starts missing deadlines — that IS
    # the frontier's right edge, reported honestly, not asserted away).
    legs = []
    for i, conc in enumerate(levels):
        leg = closed_loop(engine, args.requests, conc, sizes, traffic,
                          seed=i)
        legs.append(leg)
        print(f"[loadgen] c={conc}: {leg['rows_per_second']} rows/s "
              f"p50={leg['request_latency'].get('p50')}s "
              f"p99={leg['request_latency'].get('p99')}s "
              f"miss_rate={leg['deadline_miss_rate']}",
              file=sys.stderr)
    best_clean = max(legs, key=lambda lg: lg["rows_per_second"])

    # --- the HOT-SWAP leg: rerun the best operating point with a
    # mid-leg swap (mnist v1 -> v2 at 50% completion). The swap runs
    # on an ADMIN THREAD — load/validate/stage/warm happen off the
    # serving hot path while the closed loop keeps pumping; only the
    # atomic routing flip is shared state. This leg's throughput is
    # the HEADLINE: sustained serving at the knee, second model live,
    # swap in the middle — and the zero-downtime acceptance is zero
    # failed/shed requests across it.
    import threading

    swap_record = {}
    swap_threads: list = []

    def _swap():
        def _run():
            t = time.perf_counter()
            entry = engine.swap("mnist", paths["mnist_v2"])
            swap_record.update(
                to_version=entry.version,
                swap_seconds=round(time.perf_counter() - t, 4),
                union_changed=True)
            print(f"[loadgen] mid-leg hot swap -> mnist "
                  f"v{entry.version} in {swap_record['swap_seconds']}s "
                  "(admin thread, traffic uninterrupted)",
                  file=sys.stderr)

        th = threading.Thread(target=_run)
        swap_threads.append(th)
        th.start()

    swap_leg = closed_loop(
        engine, args.requests, best_clean["concurrency"], sizes,
        traffic, seed=len(levels), swap_at=0.5, swap_fn=_swap)
    swap_threads[0].join(timeout=120)
    assert not swap_threads[0].is_alive(), "hot swap never finished"
    print(f"[loadgen] swap leg c={swap_leg['concurrency']}: "
          f"{swap_leg['rows_per_second']} rows/s "
          f"miss_rate={swap_leg['deadline_miss_rate']}",
          file=sys.stderr)
    scrape = _scrape(engine)
    print(f"[loadgen] /metrics self-scrape ok={scrape['ok']} "
          f"({scrape['lines']} lines, {scrape['families']} families)",
          file=sys.stderr)
    assert scrape["ok"], scrape

    # Zero-downtime acceptance: across the swap leg every request
    # completed and none were shed (the knee leg had deadline headroom;
    # a swap that stalled the serving loop would blow it and show up
    # here).
    peak = swap_leg
    assert peak["failed"] == 0 and peak["expired"] == 0 \
        and peak["verdicts"]["failed"] == 0, peak
    assert engine.hot_swaps.value == 1

    # --- overload leg: tight deadline at high concurrency — the
    # shedding path must account every miss explicitly (this leg is
    # diagnostic, never the headline).
    overload = closed_loop(
        engine, max(32, args.requests // 4), max(levels) * 2, sizes,
        traffic, seed=99, deadline_ms=1.0)
    print(f"[loadgen] overload: miss_rate="
          f"{overload['deadline_miss_rate']} "
          f"(expired {overload['expired']})", file=sys.stderr)

    # --- CHAOS leg (ISSUE 13): the two crash-recovery behaviors the
    # engine now owes, exercised at the best operating point.
    chaos = None
    if args.chaos or args.smoke:
        from dpsvm_tpu.serving import ModelLoadError
        from dpsvm_tpu.testing import faults as fault_harness

        # (a) corrupted-file hot swap: a deterministically corrupted
        # copy of the v2 file must be REFUSED (ModelLoadError) with
        # the live version untouched and still serving — the
        # validate-before-flip contract under a realistic bad file.
        bad = fault_harness.corrupt_npz_file(
            paths["mnist_v2"], os.path.join(tmp, "mnist.corrupt.npz"),
            seed=5)
        live_before = engine.registry.get("mnist").version
        refused = False
        try:
            engine.swap("mnist", bad)
        except ModelLoadError as e:
            refused = True
            print(f"[loadgen] chaos: corrupted swap refused "
                  f"({str(e)[:80]}...)", file=sys.stderr)
        assert refused, "corrupted swap was ACCEPTED"
        assert engine.registry.get("mnist").version == live_before
        surviving = closed_loop(engine, max(32, args.requests // 4),
                                best_clean["concurrency"], sizes,
                                traffic, seed=7)
        assert surviving["failed"] == 0 \
            and surviving["verdicts"]["failed"] == 0 \
            and surviving["expired"] == 0, surviving

        # (b) engine kill/rehydrate-from-journal: a SECOND engine
        # constructed from the same journal must replay the exact
        # live set (versions included) and serve decisions identical
        # to the pre-crash engine, then carry traffic with zero
        # failed/expired. The first engine is deliberately NOT closed
        # first — the journal's durability cannot depend on a clean
        # shutdown.
        names = [t[0] for t in traffic]
        probe_rng = np.random.default_rng(123)
        probes = {n: probe_rng.random((8, engine.registry.get(n).d),
                                      dtype=np.float32)
                  for n in names}
        pre = {n: engine.decision(probes[n], model=n) for n in names}
        pre_versions = {e.name: e.version
                        for e in engine.registry.entries()}
        eng2 = ServingEngine(ServeConfig(
            deadline_ms=args.deadline_ms, journal_path=journal_path,
            obs=ObsConfig(enabled=args.obs, runlog_dir=args.obs_dir)))
        post_versions = {e.name: e.version
                        for e in eng2.registry.entries()}
        assert post_versions == pre_versions, (pre_versions,
                                               post_versions)
        for n in names:
            np.testing.assert_array_equal(
                eng2.decision(probes[n], model=n), pre[n])
        rehydrated = closed_loop(eng2, max(32, args.requests // 4),
                                 best_clean["concurrency"], sizes,
                                 traffic, seed=8)
        assert rehydrated["failed"] == 0 \
            and rehydrated["verdicts"]["failed"] == 0 \
            and rehydrated["expired"] == 0, rehydrated
        eng2.close()
        print(f"[loadgen] chaos: kill/rehydrate replayed "
              f"{len(post_versions)} models ({post_versions}), "
              f"decisions identical, surviving path clean",
              file=sys.stderr)
        chaos = {
            "corrupted_swap_refused": refused,
            "live_version_after_bad_swap": live_before,
            "surviving_leg": {k: surviving[k] for k in
                              ("rows_per_second", "verdicts",
                               "expired", "failed")},
            "rehydrated_versions": post_versions,
            "rehydrated_decisions_identical": True,
            "rehydrated_leg": {k: rehydrated[k] for k in
                               ("rows_per_second", "verdicts",
                                "expired", "failed")},
        }

    frontier = [{k: lg[k] for k in
                 ("concurrency", "rows_per_second",
                  "requests_per_second", "request_latency",
                  "deadline_miss_rate", "dispatches",
                  "batch_occupancy")} for lg in legs]
    result = {
        "metric": ("ServingEngine closed-loop loadgen, synthetic "
                   "MNIST-shaped 10-class OvO (45 submodels, d=784, "
                   f"pool={args.pool}) at {100 * (1 - args.aux_share):g}"
                   "% of traffic WITH a second registered covtype-OvR "
                   "model taking the rest AND a mid-leg hot swap; "
                   "requests of 1..128 rows, closed-loop concurrency "
                   f"sweep {levels}; headline = the swap leg at the "
                   "best clean operating point"),
        "value": peak["rows_per_second"],
        "unit": "examples/second",
        "examples_per_second": peak["rows_per_second"],
        "clean_peak_rows_per_second": best_clean["rows_per_second"],
        "request_latency": peak["request_latency"],
        "latency_by_model": peak.get("latency_by_model", {}),
        "deadline_ms": args.deadline_ms,
        "deadline_miss_rate": peak["deadline_miss_rate"],
        "frontier": frontier,
        "hot_swap": {**swap_record, "during_leg_failed":
                     peak["failed"], "during_leg_expired":
                     peak["expired"]},
        "overload_leg": {k: overload[k] for k in
                         ("concurrency", "requests", "expired",
                          "deadline_miss_rate", "verdicts")},
        **({"chaos": chaos} if chaos is not None else {}),
        # Union-storage stamp (ISSUE 17): the regression gate refuses
        # cross-storage comparisons (STORAGE_MISMATCH) the way it
        # refuses cross-topology ones; absent stamps derive to f32.
        "union_storage": config.effective_union_storage(),
        "engine": engine.snapshot(),
        # Occupancy-driven bucket advice (ISSUE 14 satellite; ROADMAP
        # item 2's stub closed): report-only — applying it stays
        # behind the autotune profile discipline.
        "bucket_suggestion": engine.bucket_suggestion(),
        "metrics_scrape": {k: scrape[k] for k in
                           ("status", "lines", "families",
                            "eof_terminated", "per_model_labels",
                            "ok")},
        # Device-identity stamp (ISSUE 14 satellite): the regression
        # gate refuses cross-device-kind comparisons.
        **bench._device_fields(),
        "device_numbers": ("measured" if on_tpu else
                           "pending — no TPU reachable this session; "
                           "CPU-harness wall clocks adjudicate "
                           "scheduling structure and the drift-"
                           "normalized gate only"),
        "schema_version": bench._schema_version(),
        "session_calibration": calibration,
        "smoke": bool(args.smoke),
    }
    result.update(_runlog_reconciliation(engine, engine._rows_total))
    sug = result["bucket_suggestion"]
    if sug.get("suggested_buckets"):
        print(f"[loadgen] bucket suggestion (report-only): "
              f"{sug['current_buckets']} -> {sug['suggested_buckets']} "
              f"(projected occupancy "
              f"{sug['projected_occupancy']['current']} -> "
              f"{sug['projected_occupancy']['suggested']})",
              file=sys.stderr)
    engine.close()

    gate = bench._regression_gate(result, REPO,
                                  pattern="BENCH_SERVE_r*.json",
                                  key="examples_per_second")
    result.update(gate)
    print(f"[loadgen] regression gate: {gate.get('regression_gate')} "
          f"(prev {gate.get('previous_examples_per_second')})",
          file=sys.stderr)
    if args.smoke:
        print("[loadgen] NOTE: smoke shapes are reduced (pool="
              f"{args.pool}), so the gate verdict vs the committed "
              "matched-shape baseline is informational only",
              file=sys.stderr)

    if args.out:
        art = args.out
    elif args.smoke:
        art = os.path.join(tmp, "BENCH_SERVE_smoke.json")
    else:
        nn = len(glob.glob(os.path.join(REPO, "BENCH_SERVE_r*.json"))) + 1
        art = os.path.join(REPO, f"BENCH_SERVE_r{nn:02d}.json")
    with open(art, "w") as fh:
        json.dump(result, fh, indent=1)
    print(json.dumps({k: result[k] for k in
                      ("metric", "value", "unit", "regression_gate")}))
    print(f"[loadgen] wrote {art}", file=sys.stderr)

    if not args.smoke:
        _write_md(result, os.path.basename(art))
    return 0


def _write_md(result: dict, art_name: str) -> None:
    rows = "\n".join(
        f"| {lg['concurrency']} | {lg['rows_per_second']} | "
        f"{lg['requests_per_second']} | "
        f"{lg['request_latency'].get('p50', '-')} | "
        f"{lg['request_latency'].get('p95', '-')} | "
        f"{lg['request_latency'].get('p99', '-')} | "
        f"{lg['deadline_miss_rate']} |"
        for lg in result["frontier"])
    with open(os.path.join(REPO, "BENCH_SERVE.md"), "w") as fh:
        fh.write(
            "# BENCH_SERVE — serving engine v2 (closed-loop loadgen)\n"
            "\nCommand: `python tools/loadgen.py` (artifact "
            f"`{art_name}`; history lives in git — r01 is the v1 "
            "single-model PredictServer sweep, tools/bench_serve.py). "
            "Two registered models (MNIST-OvO-shaped headline + a "
            "covtype-OvR companion), per-request deadlines, a "
            "mid-sweep zero-downtime hot swap, latency percentiles "
            "from the engine's shared Histogram instruments. CPU-"
            "harness wall clocks carry device_numbers=pending until "
            "the next TPU session.\n\n"
            "## Latency/throughput frontier (closed loop)\n\n"
            "| concurrency | rows/s | req/s | p50 s | p95 s | p99 s | "
            "miss rate |\n|---|---|---|---|---|---|---|\n"
            + rows
            + "\n\n## Headline + gate\n\n```json\n"
            + json.dumps({k: result[k] for k in
                          ("value", "unit", "request_latency",
                           "deadline_miss_rate", "hot_swap",
                           "overload_leg", "device", "device_numbers",
                           "regression_gate")
                          if k in result}, indent=1)
            + "\n```\n")
    print("[loadgen] wrote BENCH_SERVE.md", file=sys.stderr)


if __name__ == "__main__":
    sys.exit(main())
