"""SV parity vs LibSVM at the reference's exact MNIST scale (n=60000).

The reference's headline correctness claim is "same number of Support
Vectors as LibSVM" on MNIST even-odd 60000x784 (reference README.md:27,
run config reference Makefile:74). tools/parity.py demonstrates parity at
n=10000/32561; this harness closes the gap at the claim's own scale:

  * oracle: the one-time sklearn.svm.SVC run saved by tools/oracle60k.py
    (eps=0.001 — the tolerance of the reference's parity claim);
  * ours: single-chip xla / pallas / block on the real TPU, plus
    block/mesh8 in a virtual-8-device CPU child (same mechanism as
    tools/parity.py).

Pass criteria match tools/parity.py: duplicate-merged SV count within 1%
of LibSVM and >= 99.8% decision-sign agreement. Appends/replaces the
"mnist-shaped / n=60000" section of PARITY.md. Run AFTER oracle60k:
`python tools/oracle60k.py && python tools/parity60k.py`.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tools.parity_common import merged_sv as merged_sv_xy
from tools.parity_common import SECTION_60K, replace_section

SV_TOL = 0.01
SIGN_TOL = 0.998
SECTION = SECTION_60K
# epsilon is HALF the oracle's tol: LibSVM stops when its KKT gap drops
# below tol, while this framework inherits the reference's stopping rule
# b_lo > b_hi + 2*eps (svmTrainMain.cpp:310), which stops at gap <= 2*eps.
# Equal ACHIEVED gap (the quantity that determines which borderline points
# become SVs) therefore requires eps = tol/2. Measured on this dataset:
# at eps=0.001 (achieved gap 2e-3 vs the oracle's 1e-3) the count sits
# 1.3-1.8% under LibSVM's; at the aligned eps the engines land 0.4-0.6%.
CFG_KW = dict(c=10.0, gamma=0.125, epsilon=0.0005, max_iter=2_000_000)
TPU_CASES = ["xla", "pallas", "block"]


def child_main() -> int:
    """CPU child: block/mesh8 on the virtual 8-device platform."""
    from dpsvm_tpu.config import SVMConfig
    from dpsvm_tpu.data.synth import make_mnist_like
    from dpsvm_tpu.parallel.dist_smo import solve_mesh

    x, y = make_mnist_like(n=60_000, d=784, seed=7, noise=0.1)
    res = solve_mesh(x, y, SVMConfig(engine="block", working_set_size=256,
                                     **CFG_KW), num_devices=8)
    np.save(os.path.join(REPO, "artifacts", "parity60k_mesh_alpha.npy"),
            res.alpha)
    print(json.dumps({"case": "block/mesh8", "b": float(res.b),
                      "iterations": int(res.iterations),
                      "converged": bool(res.converged),
                      "device_seconds": round(res.train_seconds, 1)}),
          flush=True)
    return 0


def main() -> int:
    if "--child" in sys.argv:
        return child_main()

    from dpsvm_tpu.config import SVMConfig
    from dpsvm_tpu.data.synth import make_mnist_like
    from dpsvm_tpu.models.svm_model import SVMModel
    from dpsvm_tpu.ops.kernels import KernelParams
    from dpsvm_tpu.predict import decision_function
    from dpsvm_tpu.solver.smo import solve
    from dpsvm_tpu.utils.hostenv import cleaned_cpu_env

    with open(os.path.join(REPO, "artifacts", "oracle60k.json")) as fh:
        oracle = json.load(fh)
    z = np.load(os.path.join(REPO, "artifacts", "oracle60k.npz"))
    sk_dec = z["dec"]
    x, y = make_mnist_like(n=oracle["n"], d=oracle["d"], seed=oracle["seed"],
                           noise=oracle["noise"])

    def merged_sv(alpha):
        return merged_sv_xy(x, y, alpha)

    # Start the CPU mesh child first; it runs while the TPU cases go.
    child = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--child"],
        env=cleaned_cpu_env(8), cwd=REPO, stdout=subprocess.PIPE, text=True)

    rows = []

    def add_row(case, alpha, rec):
        dec = decision_function(
            SVMModel.from_dense(x, y, alpha, rec["b"],
                                KernelParams("rbf", CFG_KW["gamma"])), x)
        msv = merged_sv(alpha)
        sv_dev = abs(msv - oracle["merged_sv"]) / oracle["merged_sv"]
        agree = float(np.mean(np.sign(dec) == np.sign(sk_dec)))
        acc = float(np.mean(np.where(dec >= 0, 1, -1) == y))
        ok = rec["converged"] and sv_dev <= SV_TOL and agree >= SIGN_TOL
        rows.append(dict(case=case, n_sv=int((alpha > 0).sum()), msv=msv,
                         sv_dev=sv_dev, agree=agree, acc=acc,
                         iters=rec["iterations"],
                         secs=rec["device_seconds"], ok=ok))
        print(f"[60k] {case:12s} n_sv={rows[-1]['n_sv']} merged={msv} "
              f"(dev {sv_dev * 100:.2f}%) agree={agree * 100:.2f}% "
              f"acc={acc:.4f} iters={rec['iterations']} "
              f"{'OK' if ok else 'FAIL'}", flush=True)

    for engine in TPU_CASES:
        cfg = SVMConfig(engine=engine, working_set_size=256, **CFG_KW)
        res = solve(x, y, cfg)
        add_row(f"{engine}/single",
                res.alpha, dict(b=res.b, iterations=int(res.iterations),
                                converged=bool(res.converged),
                                device_seconds=round(res.train_seconds, 2)))

    out, _ = child.communicate(timeout=7200)
    if child.returncode != 0:
        raise RuntimeError("mesh child failed")
    rec = json.loads([l for l in out.splitlines() if l.startswith("{")][-1])
    alpha_mesh = np.load(os.path.join(REPO, "artifacts",
                                      "parity60k_mesh_alpha.npy"))
    add_row("block/mesh8", alpha_mesh, rec)

    lines = [
        SECTION, "",
        f"Oracle: sklearn.svm.SVC at the same pinned hyperparameters on "
        f"the benchmark dataset (make_mnist_like seed=7 noise=0.1) at "
        f"tol=0.001; ours run at eps=0.0005 so both stop at the same "
        f"ACHIEVED KKT gap of 1e-3 (LibSVM stops at gap < tol, the "
        f"reference rule b_lo > b_hi + 2*eps at gap <= 2*eps) — "
        f"**{oracle['n_sv']} SVs** ({oracle['merged_sv']} merged), train "
        f"accuracy {oracle['acc']:.4f}, fit in {oracle['seconds']:.0f} s "
        f"(tools/oracle60k.py; single-chip rows ran on the real TPU, the "
        f"mesh row on the virtual 8-device CPU platform).", "",
        "| engine/backend | n_sv | merged | Δmerged | sign agree | "
        "train acc | pair updates | device s | status |",
        "|---|---|---|---|---|---|---|---|---|",
    ]
    for r in rows:
        lines.append(
            f"| {r['case']} | {r['n_sv']} | {r['msv']} | "
            f"{r['sv_dev'] * 100:.2f}% | {r['agree'] * 100:.2f}% | "
            f"{r['acc']:.4f} | {r['iters']} | {r['secs']} | "
            f"{'OK' if r['ok'] else '**FAIL**'} |")
    lines.append("")

    path = os.path.join(REPO, "PARITY.md")
    replace_section(path, SECTION, lines)
    failures = sum(not r["ok"] for r in rows)
    print(f"wrote {path}; {'ALL OK' if not failures else f'{failures} FAILURES'}")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
