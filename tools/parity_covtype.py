"""Covtype-shaped parity row at an oracle-tractable subsample.

The covtype artifact (BENCH_COVTYPE.md) runs the reference's stress
config (c=2048, gamma=0.03125, eps=0.001 — reference Makefile:77) at
n=500k, where no LibSVM oracle is tractable; this harness anchors the
same distribution/hyperparameters against sklearn.svm.SVC at a
subsampled n (default 50k), appending a "covtype-shaped" section to
PARITY.md (same merged-SV + sign-agreement criteria and the same
achieved-KKT-gap alignment as tools/parity60k.py: ours at eps=tol/2).

Two phases so the slow CPU oracle can run while the TPU works:
  `python tools/parity_covtype.py --oracle`   (CPU, writes artifacts/)
  `python tools/parity_covtype.py`            (TPU cases + PARITY.md)
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import jax
import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tools.parity_common import merged_sv, replace_section

SV_TOL = 0.01
SIGN_TOL = 0.998
C, GAMMA, TOL = 2048.0, 0.03125, 1e-3
SECTION = ("## covtype-shaped / subsampled "
           "(achieved KKT gap 1e-3; SV parity asserted)")


def make_data(n: int):
    """The first n rows of the covtype BENCHMARK generator — imported,
    not copied, so this anchor can never drift from the benchmark's
    distribution."""
    from tools.bench_covtype import make_data as bench_make_data

    x, y = bench_make_data()
    return x[:n], y[:n]


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--oracle", action="store_true")
    ap.add_argument("-n", type=int, default=50_000)
    args = ap.parse_args()
    outdir = os.path.join(REPO, "artifacts")
    os.makedirs(outdir, exist_ok=True)
    opath = os.path.join(outdir, f"oracle_covtype{args.n}")

    if args.oracle:
        from sklearn.svm import SVC

        x, y = make_data(args.n)
        print(f"[oracle] SVC(C={C}, gamma={GAMMA}, tol={TOL}) on "
              f"{args.n}x54 ...", flush=True)
        t0 = time.perf_counter()
        sk = SVC(C=C, gamma=GAMMA, tol=TOL, cache_size=8000).fit(x, y)
        secs = time.perf_counter() - t0
        alpha = np.zeros(args.n)
        alpha[sk.support_] = np.abs(sk.dual_coef_[0])
        np.savez(opath + ".npz", alpha=alpha, dec=sk.decision_function(x))
        summary = dict(n=args.n, n_sv=int(sk.n_support_.sum()),
                       merged_sv=merged_sv(x, y, alpha),
                       acc=float(sk.score(x, y)), seconds=round(secs, 1))
        with open(opath + ".json", "w") as fh:
            json.dump(summary, fh)
        print(f"[oracle] done: {json.dumps(summary)}", flush=True)
        return 0

    from dpsvm_tpu.config import SVMConfig
    from dpsvm_tpu.solver.smo import solve

    with open(opath + ".json") as fh:
        oracle = json.load(fh)
    z = np.load(opath + ".npz")
    x, y = make_data(args.n)

    rows = []

    def reconstruct_f64(alpha):
        """Exact gradient from alpha in float64 (tiled on host):
        f_i = sum_j alpha_j y_j K_ij - y_i. The LibSVM move (its solver
        reconstructs its gradient too): the solve legs maintain f
        incrementally in fp32, whose drift floors the resolvable gap at
        ~2e-3 on this extreme-C problem; reconstruction resets the drift
        so convergence is judged on the TRUE gap."""
        x64 = x.astype(np.float64)
        ay = (alpha.astype(np.float64) * y)
        sq = (x64 ** 2).sum(1)
        f = np.empty(len(y), np.float64)
        for i0 in range(0, len(y), 4096):
            t = x64[i0:i0 + 4096]
            d2 = np.maximum(sq[i0:i0 + 4096, None] + sq[None, :]
                            - 2.0 * (t @ x64.T), 0.0)
            f[i0:i0 + 4096] = np.exp(-GAMMA * d2) @ ay
        return f - y

    from dpsvm_tpu.ops.select import extrema_np

    # Per-pair engines only, by MEASUREMENT: at this extreme C the block
    # engine's restricted working sets cycle at the tail (gap ~3 after
    # 460M subproblem pairs) while per-pair global-MVP passes gap 0.026
    # by 8M pairs. Each case runs in 8M-pair legs with an exact float64
    # gradient reconstruction between legs; convergence is declared on
    # the RECONSTRUCTED gap (the fp32 carried gap floors at ~2e-3 and,
    # pushed past its floor, random-walks alpha — measured: 26M
    # uninterrupted pairs left a state whose carried gap read 0.0019
    # while the true decision function agreed with the oracle on only
    # 59% of signs).
    for engine, sel in (("xla", "second_order"), ("xla", "mvp")):
        state_p = os.path.join(outdir,
                               f"paritystate_covtype{args.n}_{engine}_{sel}.npz")
        leg_pairs0 = 2_000_000
        if os.path.exists(state_p):  # resume across tool restarts
            zs = np.load(state_p)
            alpha_i = zs["alpha"].astype(np.float32)
            total_pairs, total_secs = int(zs["pairs"]), float(zs["secs"])
            if "leg_pairs" in zs:
                # Floor the resumed budget: a fully-shrunk saved budget
                # would end the loop before a (re)tightened inner eps
                # gets a chance to close the last 1e-4.
                leg_pairs0 = max(int(zs["leg_pairs"]), 500_000)
            f64 = reconstruct_f64(alpha_i)
            f_i = f64.astype(np.float32)
            b_hi_t, b_lo_t = extrema_np(f64, alpha_i, y, (C, C))
            gap = float(b_lo_t - b_hi_t)
            print(f"  [resume] TRUE gap={gap:.4f} pairs={total_pairs}",
                  flush=True)
        else:
            alpha_i, f_i = None, None
            total_pairs, total_secs = 0, 0.0
            gap = float("inf")
        # ADAPTIVE leg budget: the fp32 drift accumulated within one leg
        # scales with the leg's pair count and floors the true gap a leg
        # can reach (measured: 8M-pair legs asymptote at ~0.07-0.08 true
        # gap while their carried gap reads ~1e-3). When a leg's true-gap
        # improvement falls under 30%, halve the next leg's budget — the
        # drift floor halves with it and the iteration resumes geometric
        # progress at finer resolution.
        leg_pairs = leg_pairs0
        for leg in range(60):
            if gap <= TOL or leg_pairs < 62_500:
                break
            # The solver's own (carried-gap) stop aims BELOW the true
            # target: per-leg fp32 drift adds ~1-2e-4 to the
            # reconstructed gap, so carried-converging at exactly the
            # target stalls the true gap just above it (measured
            # 0.0011-0.0012 vs 0.0010).
            cfg = SVMConfig(c=C, gamma=GAMMA, epsilon=0.35 * TOL,
                            max_iter=leg_pairs, engine=engine,
                            selection=sel, dtype="float32",
                            chunk_iters=250_000)
            alpha_prev, f_prev = alpha_i, f_i
            recon_prev = ((f64, b_hi_t, b_lo_t)
                          if np.isfinite(gap) else None)
            try:
                # The heartbeat keeps the solve OBSERVED: without it the
                # whole leg runs as one ~45 s dispatch, which the
                # degraded tunnel kills (~6 s chunked dispatches pass).
                res = solve(x, y, cfg, alpha_init=alpha_i, f_init=f_i,
                            callback=lambda it, bh, bl, st: print(
                                f"    ... {it}", flush=True))
            except jax.errors.JaxRuntimeError as e:
                # Tunnel fault mid-leg: the client backend is dead for
                # this process. Exit fast; the retry wrapper restarts and
                # the resume branch reloads the last reconstructed state.
                # Anything that is NOT a device-runtime error propagates
                # with its traceback — a deterministic bug must never
                # masquerade as infrastructure and loop the wrapper.
                print(f"  [leg {leg}] device fault ({e!r:.200}); "
                      f"exiting for wrapper resume", flush=True)
                sys.exit(3)
            total_pairs += int(res.iterations)
            total_secs += res.train_seconds
            alpha_i = res.alpha
            prev = gap
            f64 = reconstruct_f64(alpha_i)
            b_hi_t, b_lo_t = extrema_np(f64, alpha_i, y, (C, C))
            gap = float(b_lo_t - b_hi_t)
            print(f"  [leg {leg}] budget={leg_pairs} "
                  f"carried={float(res.b_lo - res.b_hi):.4f} "
                  f"TRUE gap={gap:.4f} pairs={total_pairs}", flush=True)
            if gap > prev and np.isfinite(prev):
                # REJECT a regressed leg: its drift did more harm than
                # its optimization did good (measured at mid-phase gaps:
                # a 2M-pair leg moved the true gap 2.2 -> 2.5). Revert
                # to the pre-leg state and retry at half the budget —
                # the true gap descends monotonically by construction.
                print(f"  [leg {leg}] REJECTED (prev {prev:.4f}); "
                      f"halving to {leg_pairs // 2}", flush=True)
                alpha_i, f_i, gap = alpha_prev, f_prev, prev
                if recon_prev is not None:
                    # The post-loop b/decision evaluation must see the
                    # KEPT state's reconstruction, not the rejected one.
                    f64, b_hi_t, b_lo_t = recon_prev
                leg_pairs //= 2
                # Persist the halving: a fault before the next good leg
                # must not make the resume re-run a budget already
                # proven regressing.
                tmp = state_p + ".tmp.npz"
                np.savez(tmp, alpha=alpha_i, pairs=total_pairs,
                         secs=total_secs, leg_pairs=leg_pairs)
                os.replace(tmp, state_p)
                continue
            if gap > 0.85 * prev:
                # Near the drift floor: finer legs resolve further.
                leg_pairs //= 2
            # Atomic write (tmp + os.replace, like utils/checkpoint.py):
            # a mid-write kill must never leave a truncated state file
            # that wedges every subsequent resume. leg_pairs rides along
            # so restarts don't re-run budgets already proven drift-
            # floored.
            tmp = state_p + ".tmp.npz"  # .npz suffix: savez appends
            np.savez(tmp, alpha=alpha_i, pairs=total_pairs,  # otherwise
                     secs=total_secs, leg_pairs=leg_pairs)
            os.replace(tmp, state_p)
            f_i = f64.astype(np.float32)
        converged = gap <= TOL
        b = float((b_lo_t + b_hi_t) / 2.0)
        np.savez(os.path.join(outdir,
                              f"parity_covtype{args.n}_{engine}_{sel}.npz"),
                 alpha=alpha_i, b=b, gap=gap)
        # Decision values in FLOAT64, directly from the reconstructed
        # gradient: dec_i = sum_j a_j y_j K_ij - b = f64_i + y_i - b.
        # At this C the fp32 batched predictor's accumulation noise
        # (23k terms of magnitude ~1500 summing to ~1) swamps the signs
        # — measured 59% agreement from an alpha whose merged SV count
        # matches the oracle to 0.05%; the oracle's own decision values
        # are float64 (sklearn). Apples to apples means f64 vs f64.
        dec = f64 + y - b
        msv = merged_sv(x, y, alpha_i)
        sv_dev = abs(msv - oracle["merged_sv"]) / oracle["merged_sv"]
        agree = float(np.mean(np.sign(dec) == np.sign(z["dec"])))
        acc = float(np.mean(np.where(dec >= 0, 1, -1) == y))
        ok = converged and sv_dev <= SV_TOL and agree >= SIGN_TOL
        label = f"{engine}/{sel} (per-pair)"
        rows.append((label, int((alpha_i > 0).sum()), msv, sv_dev, agree,
                     acc, total_pairs, round(total_secs, 2), ok))
        print(f"[covtype{args.n}] {label:20s} n_sv={rows[-1][1]} "
              f"merged={msv} (dev {sv_dev * 100:.2f}%) "
              f"agree={agree * 100:.2f}% acc={acc:.4f} "
              f"TRUE gap={gap:.4f} pairs={total_pairs} "
              f"{'OK' if ok else 'FAIL'}", flush=True)

    lines = [
        SECTION, "",
        f"The BENCH_COVTYPE.md distribution and hyperparameters "
        f"(c={C:g}, gamma={GAMMA:g}) at n={args.n} (first rows of the "
        f"same generator), where the LibSVM oracle is tractable. Oracle: "
        f"**{oracle['n_sv']} SVs** ({oracle['merged_sv']} merged), train "
        f"accuracy {oracle['acc']:.4f}, fit in {oracle['seconds']:.0f} s; "
        f"ours at eps=tol/2, solved in adaptively-shrinking legs with "
        f"an exact float64 gradient reconstruction between legs (the "
        f"LibSVM move: fp32 incremental gradients drift — measured "
        f"carried gap 0.005 vs true 1.1 after one 8M-pair leg — and "
        f"the per-leg drift floors the reachable true gap, so leg "
        f"budgets halve whenever improvement stalls) and convergence "
        f"judged ONLY on the RECONSTRUCTED gap. Rows ran on the real TPU (per-pair "
        f"engines — the block engine's working sets cycle at this C's "
        f"tail; see BENCH_COVTYPE.md's engine-semantics note).", "",
        "| engine/selection | n_sv | merged | Δmerged | sign agree | "
        "train acc | pair updates | device s | status |",
        "|---|---|---|---|---|---|---|---|---|",
    ]
    for (label, n_sv, msv, sv_dev, agree, acc, iters, secs, ok) in rows:
        lines.append(f"| {label} | {n_sv} | {msv} | {sv_dev * 100:.2f}% | "
                     f"{agree * 100:.2f}% | {acc:.4f} | {iters} | {secs} | "
                     f"{'OK' if ok else '**FAIL**'} |")
    lines += ["",
              "Status is the STRICT conjunction: reconstructed gap <= "
              "1e-3 AND merged-SV delta <= 1% AND sign agreement >= "
              "99.8%. A row can fail ONLY the gap test and still match "
              "the oracle on every parity criterion — the leg scheme's "
              "reachable gap is floored by per-leg fp32 drift at its "
              "final leg size, and the harness stops rather than "
              "claiming tighter convergence than it can verify.", ""]

    path = os.path.join(REPO, "PARITY.md")
    replace_section(path, SECTION, lines)
    failures = sum(not r[-1] for r in rows)
    print(f"wrote {path}; {'ALL OK' if not failures else f'{failures} FAILURES'}")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
