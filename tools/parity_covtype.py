"""Covtype-shaped parity row at an oracle-tractable subsample.

The covtype artifact (BENCH_COVTYPE.md) runs the reference's stress
config (c=2048, gamma=0.03125, eps=0.001 — reference Makefile:77) at
n=500k, where no LibSVM oracle is tractable; this harness anchors the
same distribution/hyperparameters against sklearn.svm.SVC at a
subsampled n (default 50k), appending a "covtype-shaped" section to
PARITY.md (same merged-SV + sign-agreement criteria and the same
achieved-KKT-gap alignment as tools/parity60k.py: ours at eps=tol/2).

Two phases so the slow CPU oracle can run while the TPU works:
  `python tools/parity_covtype.py --oracle`   (CPU, writes artifacts/)
  `python tools/parity_covtype.py`            (TPU cases + PARITY.md)
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tools.parity_common import merged_sv, replace_section

SV_TOL = 0.01
SIGN_TOL = 0.998
C, GAMMA, TOL = 2048.0, 0.03125, 1e-3
SECTION = ("## covtype-shaped / subsampled "
           "(achieved KKT gap 1e-3; SV parity asserted)")


def make_data(n: int):
    """The first n rows of the covtype BENCHMARK generator — imported,
    not copied, so this anchor can never drift from the benchmark's
    distribution."""
    from tools.bench_covtype import make_data as bench_make_data

    x, y = bench_make_data()
    return x[:n], y[:n]


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--oracle", action="store_true")
    ap.add_argument("-n", type=int, default=50_000)
    args = ap.parse_args()
    outdir = os.path.join(REPO, "artifacts")
    os.makedirs(outdir, exist_ok=True)
    opath = os.path.join(outdir, f"oracle_covtype{args.n}")

    if args.oracle:
        from sklearn.svm import SVC

        x, y = make_data(args.n)
        print(f"[oracle] SVC(C={C}, gamma={GAMMA}, tol={TOL}) on "
              f"{args.n}x54 ...", flush=True)
        t0 = time.perf_counter()
        sk = SVC(C=C, gamma=GAMMA, tol=TOL, cache_size=8000).fit(x, y)
        secs = time.perf_counter() - t0
        alpha = np.zeros(args.n)
        alpha[sk.support_] = np.abs(sk.dual_coef_[0])
        np.savez(opath + ".npz", alpha=alpha, dec=sk.decision_function(x))
        summary = dict(n=args.n, n_sv=int(sk.n_support_.sum()),
                       merged_sv=merged_sv(x, y, alpha),
                       acc=float(sk.score(x, y)), seconds=round(secs, 1))
        with open(opath + ".json", "w") as fh:
            json.dump(summary, fh)
        print(f"[oracle] done: {json.dumps(summary)}", flush=True)
        return 0

    from dpsvm_tpu.config import SVMConfig
    from dpsvm_tpu.models.svm_model import SVMModel
    from dpsvm_tpu.ops.kernels import KernelParams
    from dpsvm_tpu.predict import decision_function
    from dpsvm_tpu.solver.smo import solve

    with open(opath + ".json") as fh:
        oracle = json.load(fh)
    z = np.load(opath + ".npz")
    x, y = make_data(args.n)

    rows = []
    for engine, sel in (("xla", "mvp"), ("block", "mvp"),
                        ("block", "second_order")):
        # The convergence budget is generous (the 20k subsample needed
        # >50M pairs at this C); chunked via the heartbeat callback so
        # the tunnel never sees one giant dispatch.
        cfg = SVMConfig(c=C, gamma=GAMMA, epsilon=TOL / 2,
                        max_iter=1_000_000_000, engine=engine,
                        selection=sel, working_set_size=512,
                        inner_iters=4096, dtype="float32",
                        chunk_iters=10_000_000)
        beat = lambda it, bh, bl, st: print(
            f"    ... {it} pairs gap={bl - bh:.4f}", flush=True)
        res = solve(x, y, cfg, callback=beat)
        model = SVMModel.from_dense(x, y, res.alpha, res.b,
                                    KernelParams("rbf", GAMMA))
        dec = decision_function(model, x)
        msv = merged_sv(x, y, res.alpha)
        sv_dev = abs(msv - oracle["merged_sv"]) / oracle["merged_sv"]
        agree = float(np.mean(np.sign(dec) == np.sign(z["dec"])))
        acc = float(np.mean(np.where(dec >= 0, 1, -1) == y))
        ok = res.converged and sv_dev <= SV_TOL and agree >= SIGN_TOL
        label = f"{engine}/{sel}"
        rows.append((label, int((res.alpha > 0).sum()), msv, sv_dev, agree,
                     acc, int(res.iterations),
                     round(res.train_seconds, 2), ok))
        print(f"[covtype{args.n}] {label:20s} n_sv={rows[-1][1]} "
              f"merged={msv} (dev {sv_dev * 100:.2f}%) "
              f"agree={agree * 100:.2f}% acc={acc:.4f} "
              f"iters={res.iterations} {'OK' if ok else 'FAIL'}",
              flush=True)

    lines = [
        SECTION, "",
        f"The BENCH_COVTYPE.md distribution and hyperparameters "
        f"(c={C:g}, gamma={GAMMA:g}) at n={args.n} (first rows of the "
        f"same generator), where the LibSVM oracle is tractable. Oracle: "
        f"**{oracle['n_sv']} SVs** ({oracle['merged_sv']} merged), train "
        f"accuracy {oracle['acc']:.4f}, fit in {oracle['seconds']:.0f} s; "
        f"ours at eps=tol/2 (equal achieved gap, see the full-scale "
        f"section above). Rows ran on the real TPU.", "",
        "| engine/selection | n_sv | merged | Δmerged | sign agree | "
        "train acc | pair updates | device s | status |",
        "|---|---|---|---|---|---|---|---|---|",
    ]
    for (label, n_sv, msv, sv_dev, agree, acc, iters, secs, ok) in rows:
        lines.append(f"| {label} | {n_sv} | {msv} | {sv_dev * 100:.2f}% | "
                     f"{agree * 100:.2f}% | {acc:.4f} | {iters} | {secs} | "
                     f"{'OK' if ok else '**FAIL**'} |")
    lines.append("")

    replace_section(os.path.join(REPO, "PARITY.md"), SECTION, lines)
    failures = sum(not r[-1] for r in rows)
    print(f"wrote {path}; {'ALL OK' if not failures else f'{failures} FAILURES'}")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
