"""Covtype-shaped parity row at an oracle-tractable subsample.

The covtype artifact (BENCH_COVTYPE.md) runs the reference's stress
config (c=2048, gamma=0.03125, eps=0.001 — reference Makefile:77) at
n=500k, where no LibSVM oracle is tractable; this harness anchors the
same distribution/hyperparameters against sklearn.svm.SVC at a
subsampled n (default 50k), appending a "covtype-shaped" section to
PARITY.md (same merged-SV + sign-agreement criteria and the same
achieved-KKT-gap alignment as tools/parity60k.py: ours at eps=tol/2).

Since round 4 this is a THIN wrapper: the adaptive f64-reconstruction
legs that round 3 implemented here live inside the solver
(config.reconstruct_every + config.compensated + the auto-escalated
matmul precision, solver/reconstruct.py) — each row is ONE solve()
call, the same way the reference runs its covtype config in one tool
invocation (reference svmTrainMain.cpp:142-365).

Two phases so the slow CPU oracle can run while the TPU works:
  `python tools/parity_covtype.py --oracle`   (CPU, writes artifacts/)
  `python tools/parity_covtype.py`            (TPU cases + PARITY.md)

On a tunnel fault the process exits with code 3; rerunning resumes from
the solver's own certified checkpoint.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import jax
import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tools.parity_common import (SECTION_COVTYPE, merged_sv,
                                 replace_section)

SV_TOL = 0.01
SIGN_TOL = 0.998
C, GAMMA, TOL = 2048.0, 0.03125, 1e-3
SECTION = SECTION_COVTYPE


def make_data(n: int):
    """The first n rows of the covtype BENCHMARK generator — imported,
    not copied, so this anchor can never drift from the benchmark's
    distribution."""
    from tools.bench_covtype import make_data as bench_make_data

    x, y = bench_make_data()
    return x[:n], y[:n]


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--oracle", action="store_true")
    ap.add_argument("-n", type=int, default=50_000)
    ap.add_argument("--max-pairs", type=int, default=60_000_000)
    ap.add_argument("--leg", type=int, default=2_000_000)
    args = ap.parse_args()
    outdir = os.path.join(REPO, "artifacts")
    os.makedirs(outdir, exist_ok=True)
    opath = os.path.join(outdir, f"oracle_covtype{args.n}")

    if args.oracle:
        from sklearn.svm import SVC

        x, y = make_data(args.n)
        print(f"[oracle] SVC(C={C}, gamma={GAMMA}, tol={TOL}) on "
              f"{args.n}x54 ...", flush=True)
        t0 = time.perf_counter()
        sk = SVC(C=C, gamma=GAMMA, tol=TOL, cache_size=8000).fit(x, y)
        secs = time.perf_counter() - t0
        alpha = np.zeros(args.n)
        alpha[sk.support_] = np.abs(sk.dual_coef_[0])
        np.savez(opath + ".npz", alpha=alpha, dec=sk.decision_function(x))
        summary = dict(n=args.n, n_sv=int(sk.n_support_.sum()),
                       merged_sv=merged_sv(x, y, alpha),
                       acc=float(sk.score(x, y)), seconds=round(secs, 1))
        with open(opath + ".json", "w") as fh:
            json.dump(summary, fh)
        print(f"[oracle] done: {json.dumps(summary)}", flush=True)
        return 0

    from dpsvm_tpu.config import SVMConfig
    from dpsvm_tpu.solver.smo import solve

    with open(opath + ".json") as fh:
        oracle = json.load(fh)
    z = np.load(opath + ".npz")
    x, y = make_data(args.n)

    rows = []
    # Since round 5 these rows run the DEFAULT throughput engine
    # (engine='block'): the reconstruction legs detect the block
    # engine's measured extreme-C cycling (a full leg failing to halve
    # the true gap) and hand the tail to the per-pair engine
    # automatically (solver/reconstruct.py hybrid switch), which rides
    # the resident-Gram path — per-pair kernel rows become row gathers
    # of the on-device (n, n) Gram (solver/smo.py _resolve_gram).
    # Stopping: the solver's reconstruction legs judge the TRUE
    # (float64) gap; ours runs at eps=tol/2 so the achieved gap aligns
    # with LibSVM's tol (b_lo > b_hi + 2*eps rule).
    unrecorded_wall = 0.0
    for engine, sel in (("block", "second_order"), ("block", "mvp")):
        ck = os.path.join(outdir,
                          f"parityck_covtype{args.n}_{engine}_{sel}.npz")
        # Device seconds accumulate across fault-reruns in a sidecar:
        # res.iterations is cumulative (checkpoint resume) but
        # res.train_seconds covers only THIS process. A fault loses the
        # in-flight attempt's device time; its wall-clock is recorded so
        # the narrative can flag incomplete timing instead of silently
        # inflating pairs/s.
        sc = ck + ".secs.json"
        prior = {"device_s": 0.0, "unrecorded_wall_s": 0.0}
        if os.path.exists(sc):
            with open(sc) as fh:
                prior.update(json.load(fh))
        # retry_faults=0: this tool has its own exit-fast/rerun recovery
        # protocol, and an IN-process retry would silently swallow the
        # faulted attempt's device seconds that the sidecar accounting
        # exists to flag.
        cfg = SVMConfig(c=C, gamma=GAMMA, epsilon=TOL / 2,
                        max_iter=args.max_pairs, engine=engine,
                        selection=sel, dtype="float32",
                        compensated=True, reconstruct_every=args.leg,
                        chunk_iters=250_000, checkpoint_every=1,
                        retry_faults=0, verbose=True)
        last = [0.0]

        def heartbeat(it, bh, bl, st):
            now = time.perf_counter()
            if now - last[0] > 30:
                last[0] = now
                print(f"    ... {it} pairs, carried gap {bl - bh:.5f}",
                      flush=True)

        t_attempt = time.perf_counter()
        try:
            res = solve(x, y, cfg, callback=heartbeat,
                        checkpoint_path=ck, resume=True)
        except jax.errors.JaxRuntimeError as e:
            # Tunnel fault: the client backend is dead for this process.
            # Exit fast; a rerun resumes from the certified checkpoint.
            # Non-runtime errors propagate — a deterministic bug must
            # never masquerade as infrastructure.
            prior["unrecorded_wall_s"] += time.perf_counter() - t_attempt
            with open(sc, "w") as fh:
                json.dump(prior, fh)
            print(f"  device fault ({e!r:.200}); rerun to resume",
                  flush=True)
            return 3
        device_s = prior["device_s"] + res.train_seconds
        with open(sc, "w") as fh:
            json.dump({"device_s": device_s,
                       "unrecorded_wall_s": prior["unrecorded_wall_s"]}, fh)
        unrecorded_wall += prior["unrecorded_wall_s"]

        gap = res.stats["true_gap"]
        switch = res.stats.get("hybrid_switch_pairs")
        b = res.b
        np.savez(os.path.join(outdir,
                              f"parity_covtype{args.n}_{engine}_{sel}.npz"),
                 alpha=res.alpha, b=b, gap=gap)
        # Decision values from the RECONSTRUCTED gradient:
        # dec_i = f_i + y_i - b (exact in f64 up to one f32 rounding of
        # the stored stats["f"]). The fp32 batched predictor's
        # accumulation noise swamps extreme-C signs (round-3 measurement:
        # 59% agreement fp32 vs 99.99% f64); the oracle's decision values
        # are float64 too (sklearn) — apples to apples.
        dec = res.stats["f"].astype(np.float64) + y - b
        msv = merged_sv(x, y, res.alpha)
        sv_dev = abs(msv - oracle["merged_sv"]) / oracle["merged_sv"]
        agree = float(np.mean(np.sign(dec) == np.sign(z["dec"])))
        acc = float(np.mean(np.where(dec >= 0, 1, -1) == y))
        ok = res.converged and sv_dev <= SV_TOL and agree >= SIGN_TOL
        label = (f"block→per-pair hybrid/{sel}" if engine == "block"
                 else f"{engine}/{sel} (per-pair)")
        rows.append((label, int((res.alpha > 0).sum()), msv, sv_dev, agree,
                     acc, int(res.iterations), round(device_s, 2), ok))
        print(f"[covtype{args.n}] {label:28s} n_sv={rows[-1][1]} "
              f"merged={msv} (dev {sv_dev * 100:.2f}%) "
              f"agree={agree * 100:.2f}% acc={acc:.4f} "
              f"TRUE gap={gap:.5f} pairs={res.iterations} "
              f"legs={res.stats['legs']} switch={switch} "
              f"recon_s={res.stats['reconstruct_seconds']:.0f} "
              f"{'OK' if ok else 'FAIL'}", flush=True)

    lines = [
        SECTION, "",
        f"The BENCH_COVTYPE.md distribution and hyperparameters "
        f"(c={C:g}, gamma={GAMMA:g}) at n={args.n} (first rows of the "
        f"same generator), where the LibSVM oracle is tractable. Oracle: "
        f"**{oracle['n_sv']} SVs** ({oracle['merged_sv']} merged), train "
        f"accuracy {oracle['acc']:.4f}, fit in {oracle['seconds']:.0f} s. "
        f"Ours: ONE `solve()` call per row at eps=tol/2 with the in-solver "
        f"extreme-C accuracy mode (`compensated=True, "
        f"reconstruct_every={args.leg}`, matmul precision auto-escalated "
        f"to 'highest'): the solver runs f64 gradient-reconstruction legs, "
        f"rejects regressed legs, and judges convergence ONLY on the "
        f"reconstructed gap — the round-3 external harness, productized "
        f"(solver/reconstruct.py). Since round 5 the rows start on the "
        f"DEFAULT throughput engine (engine='block'); the legs detect "
        f"the block engine's measured extreme-C cycling (a full leg "
        f"failing to halve the true gap — BENCH_COVTYPE.md's "
        f"engine-semantics note) and hand the tail to the per-pair "
        f"engine automatically, which runs on the resident (n, n) "
        f"device Gram (config.gram_resident auto) so each pair costs "
        f"row GATHERS instead of two 6-pass MXU matvecs — measured "
        f"49.7 -> 22 us/pair (PROFILE.md round-5). Rows ran on the "
        f"real TPU in ONE solve() call each.", "",
        "| engine/selection | n_sv | merged | Δmerged | sign agree | "
        "train acc | pair updates | device s | status |",
        "|---|---|---|---|---|---|---|---|---|",
    ]
    for (label, n_sv, msv, sv_dev, agree, acc, iters, secs, ok) in rows:
        lines.append(f"| {label} | {n_sv} | {msv} | {sv_dev * 100:.2f}% | "
                     f"{agree * 100:.2f}% | {acc:.4f} | {iters} | {secs} | "
                     f"{'OK' if ok else '**FAIL**'} |")
    lines += ["",
              "Status is the STRICT conjunction: reconstructed gap <= "
              "1e-3 (the solver's `converged`, judged on the float64 "
              "reconstruction) AND merged-SV delta <= 1% AND sign "
              "agreement >= 99.8%."]
    if unrecorded_wall > 0:
        lines.append(
            f"Timing caveat: ~{unrecorded_wall:.0f} wall-seconds of "
            f"faulted-attempt work are NOT in the device-s column (their "
            f"pairs resumed from checkpoints) — treat device seconds as "
            f"a lower bound for those rows.")
    lines.append("")

    path = os.path.join(REPO, "PARITY.md")
    replace_section(path, SECTION, lines)
    failures = sum(not r[-1] for r in rows)
    print(f"wrote {path}; {'ALL OK' if not failures else f'{failures} FAILURES'}")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
