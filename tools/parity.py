"""Mid-scale LibSVM parity harness -> PARITY.md.

The reference's headline correctness claim is "same number of Support
Vectors as LibSVM" (reference README.md:27), demonstrated by hand on
Adult/MNIST. This harness makes that claim checkable at mid scale
(5-10k rows) under the reference's own pinned hyperparameters:

  * mnist-shaped  (d=784, c=10,  gamma=0.125, eps=0.01  — ref Makefile:74)
  * adult-shaped  (d=123, c=100, gamma=0.5,   eps=0.001 — ref Makefile:86)

against sklearn.svm.SVC (libsvm) as the oracle, across every engine and
backend:

  * single-chip xla / pallas / block  — run on the REAL TPU when the axon
    backend is reachable (numerics on hardware, not just CPU);
  * 8-device mesh xla / block        — run in a cleaned-environment CPU
    child with a virtual 8-device platform (the same mechanism as
    __graft_entry__.dryrun_multichip).

Each case must match LibSVM's SV count within 1% and agree on >= 99.8% of
training-set decision signs. Results are written to PARITY.md; exits
nonzero if any case fails. Run: `python tools/parity.py [--quick]`.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

SV_TOL = 0.01          # SV-count relative tolerance vs LibSVM
SIGN_TOL = 0.998       # min fraction of agreeing decision signs

# Parity methodology (measured, see PARITY.md prose):
#   * The SV-count check is duplicate-aware: identical (row, label) pairs
#     make the dual optimum a face — any split of a duplicate group's
#     summed alpha is optimal, so the RAW count is solver-path-dependent
#     (on the adult-shaped data LibSVM keeps ~9% more rows active, every
#     one a duplicate of one of ours; after merging groups the counts
#     match EXACTLY). We compare alpha>0 counts after summing alpha over
#     duplicate groups.
#   * The check runs at eps=0.001 — the tolerance of the reference's own
#     parity claim (reference README.md:23,27). At the MNIST Makefile
#     run's loose eps=0.01 the SV set is underdetermined by the stopping
#     rule itself: LibSVM against itself moves 2.4% between tol=0.01 and
#     0.003, and the disagreeing points sit on |1 - y f(x)| ~ 5e-4.
#     Configs with a looser pinned eps get an extra sv-check run.
DATASETS = {
    # name: (generator kwargs, pinned SVMConfig kwargs [ref Makefile:74,86],
    #        eps for the SV-parity run, or None if pinned eps is tight)
    "mnist-shaped": (dict(kind="mnist", d=784, seed=7),
                     dict(c=10.0, gamma=0.125, epsilon=0.01,
                          max_iter=2_000_000), 0.001),
    "adult-shaped": (dict(kind="adult", d=123, seed=13),
                     dict(c=100.0, gamma=0.5, epsilon=0.001,
                          max_iter=2_000_000), None),
}
CASES = [
    # (engine, backend, platform-child); "-pb2" suffix = pair_batch=2
    # (the batched disjoint-pair subproblem steps, SVMConfig.pair_batch)
    ("xla", "single", "tpu"),
    ("pallas", "single", "tpu"),
    ("block", "single", "tpu"),
    ("block-pb2", "single", "tpu"),
    ("xla", "mesh8", "cpu"),
    ("block", "mesh8", "cpu"),
    ("block-pb2", "mesh8", "cpu"),
]


def _make_dataset(kind: str, n: int, d: int, seed: int):
    from dpsvm_tpu.data.synth import make_adult_like, make_mnist_like

    if kind == "mnist":
        return make_mnist_like(n=n, d=d, seed=seed, noise=0.1)
    return make_adult_like(n=n, d=d, seed=seed)


def child_main(args) -> int:
    """Run inside a platform-configured child: solve the requested cases
    for one dataset, save decision values, print one JSON line per case."""
    import jax

    data = np.load(args.data)
    x, y = data["x"], data["y"]
    cfg_kw = json.loads(args.config)

    from dpsvm_tpu.config import SVMConfig
    from dpsvm_tpu.models.svm_model import SVMModel
    from dpsvm_tpu.ops.kernels import KernelParams
    from dpsvm_tpu.predict import decision_function
    from dpsvm_tpu.solver.smo import solve
    from dpsvm_tpu.parallel.dist_smo import solve_mesh

    for case in args.cases.split(","):
        engine, backend = case.split("/")
        pb = 1
        if engine.endswith("-pb2"):
            engine, pb = engine[:-4], 2
        cfg = SVMConfig(engine=engine, pair_batch=pb, **cfg_kw)
        t0 = time.perf_counter()
        if backend == "mesh8":
            res = solve_mesh(x, y, cfg, num_devices=8)
        else:
            res = solve(x, y, cfg)
        wall = time.perf_counter() - t0
        kp = KernelParams("rbf", cfg.resolve_gamma(x.shape[1]))
        model = SVMModel.from_dense(x, y, res.alpha, res.b, kp)
        dec = decision_function(model, x)
        # Filename keyed by the CASE label, not the stripped engine —
        # block and block-pb2 must not overwrite each other's artifacts.
        out = os.path.join(args.outdir,
                           f"{args.name}_{case.replace('/', '_')}.npz")
        np.savez(out, dec=dec, alpha=res.alpha)
        print(json.dumps({
            "case": case, "dataset": args.name,
            "platform": jax.devices()[0].platform,
            "b": float(res.b),
            "iterations": int(res.iterations),
            "converged": bool(res.converged),
            "device_seconds": round(res.train_seconds, 3),
            "wall_seconds": round(wall, 1),
            "artifact": out,
        }), flush=True)
    return 0


def _spawn_child(platform: str, name: str, data_path: str, cfg_kw: dict,
                 cases: list, outdir: str) -> list:
    if platform == "cpu":
        from dpsvm_tpu.utils.hostenv import cleaned_cpu_env

        env = cleaned_cpu_env(8)
    else:
        env = dict(os.environ)
    cmd = [sys.executable, os.path.abspath(__file__), "--child",
           "--name", name, "--data", data_path,
           "--config", json.dumps(cfg_kw),
           "--cases", ",".join(cases), "--outdir", outdir]
    proc = subprocess.run(cmd, env=env, cwd=REPO, capture_output=True,
                          text=True, timeout=7200)
    if proc.returncode != 0:
        sys.stderr.write(proc.stderr[-4000:])
        raise RuntimeError(
            f"{platform} child failed (rc={proc.returncode}) for {name}")
    return [json.loads(line) for line in proc.stdout.splitlines()
            if line.startswith("{")]


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--child", action="store_true")
    ap.add_argument("--name")
    ap.add_argument("--data")
    ap.add_argument("--config")
    ap.add_argument("--cases")
    ap.add_argument("--outdir")
    ap.add_argument("--quick", action="store_true",
                    help="2k rows instead of the 8k/10k defaults")
    ap.add_argument("--full", action="store_true",
                    help="adult-shaped at the reference's exact row count "
                         "(n=32561, reference Makefile:86) instead of 8k; "
                         "mnist-shaped stays at 10k (sklearn's LibSVM at "
                         "60k x 784 is hours — its real-MNIST run took "
                         "13,963 s, reference README.md:25)")
    ap.add_argument("--cpu-only", action="store_true",
                    help="run the single-chip cases on CPU too")
    ap.add_argument("--out", default=os.path.join(REPO, "PARITY.md"))
    args = ap.parse_args()
    if args.child:
        return child_main(args)

    from sklearn.svm import SVC

    rows = []
    failures = 0
    tmpdir = tempfile.mkdtemp(prefix="parity_")
    for name, (gen_kw, cfg_kw, sv_eps) in DATASETS.items():
        n = 2000 if args.quick else (10_000 if gen_kw["kind"] == "mnist"
                                     else (32_561 if args.full else 8_000))
        x, y = _make_dataset(n=n, **gen_kw)
        # Duplicate (row, label) group index for the merged SV count.
        _, inv = np.unique(x, axis=0, return_inverse=True)
        group = inv.astype(np.int64) * 2 + (y > 0)

        def merged_sv(alpha, group=group):
            s = np.zeros(group.max() + 1)
            np.add.at(s, group, np.abs(alpha))
            return int((s > 0).sum())

        data_path = os.path.join(tmpdir, f"{name}.npz")
        np.savez(data_path, x=x, y=y)

        passes = [("pinned", cfg_kw, sv_eps is None)]
        if sv_eps is not None:
            passes.append(("sv-check", dict(cfg_kw, epsilon=sv_eps), True))
        for tag, ckw, check_sv in passes:
            t0 = time.perf_counter()
            sk = SVC(C=ckw["c"], gamma=ckw["gamma"],
                     tol=ckw["epsilon"], cache_size=1000).fit(x, y)
            sk_seconds = time.perf_counter() - t0
            sk_dec = sk.decision_function(x)
            a_sk = np.zeros(n)
            a_sk[sk.support_] = np.abs(sk.dual_coef_[0])
            sk_sv = int(sk.n_support_.sum())
            sk_msv = merged_sv(a_sk)
            sk_acc = float(sk.score(x, y))
            print(f"[{name}/{tag}] n={n} eps={ckw['epsilon']} libsvm: "
                  f"n_sv={sk_sv} merged={sk_msv} acc={sk_acc:.4f} "
                  f"({sk_seconds:.0f}s)", flush=True)

            by_platform = {}
            for engine, backend, plat in CASES:
                if args.cpu_only:
                    plat = "cpu"
                by_platform.setdefault(plat, []).append(f"{engine}/{backend}")
            for plat, cases in by_platform.items():
                for rec in _spawn_child(plat, f"{name}@{tag}", data_path,
                                        ckw, cases, tmpdir):
                    z = np.load(rec["artifact"])
                    dec, alpha = z["dec"], z["alpha"]
                    n_sv = int((alpha > 0).sum())
                    msv = merged_sv(alpha)
                    sv_dev = abs(msv - sk_msv) / sk_msv
                    agree = float(np.mean(np.sign(dec) == np.sign(sk_dec)))
                    acc = float(np.mean(np.where(dec >= 0, 1, -1) == y))
                    ok = (rec["converged"] and agree >= SIGN_TOL
                          and (not check_sv or sv_dev <= SV_TOL))
                    failures += not ok
                    rows.append(dict(rec, dataset=name, phase=tag, n=n,
                                     eps=ckw["epsilon"], n_sv=n_sv, msv=msv,
                                     sk_sv=sk_sv, sk_msv=sk_msv,
                                     sk_acc=sk_acc, sv_dev=sv_dev,
                                     agree=agree, acc=acc,
                                     check_sv=check_sv, ok=ok))
                    print(f"[{name}/{tag}] {rec['case']:13s} "
                          f"({rec['platform']}): n_sv={n_sv} merged={msv} "
                          f"(dev {sv_dev * 100:.2f}%"
                          f"{'' if check_sv else ', info'}) "
                          f"agree={agree * 100:.2f}% acc={acc:.4f} "
                          f"iters={rec['iterations']} "
                          f"dev_s={rec['device_seconds']} "
                          f"{'OK' if ok else 'FAIL'}", flush=True)

    _write_md(args.out, rows, args.quick, args.full)
    print(f"wrote {args.out}; {'ALL OK' if not failures else f'{failures} FAILURES'}")
    return 1 if failures else 0


def _write_md(path: str, rows: list, quick: bool, full: bool = False) -> None:
    lines = [
        "# PARITY — LibSVM oracle at mid scale",
        "",
        "Generated by `python tools/parity.py`"
        + (" --quick" if quick else "")
        + (" --full (adult-shaped at the reference's exact n=32561, "
           "reference Makefile:86)" if full else "")
        + ". Oracle: sklearn.svm.SVC (libsvm) at the reference's pinned "
        "hyperparameters (mnist-shaped: c=10 gamma=0.125 eps=0.01, "
        "reference Makefile:74; adult-shaped: c=100 gamma=0.5 eps=0.001, "
        "reference Makefile:86). Single-chip rows run on the real TPU; "
        "mesh8 rows on the 8-device virtual CPU platform.",
        "",
        "Pass criteria:",
        "",
        "* decision-sign agreement >= 99.8% on the training set (every "
        "pass);",
        "* **duplicate-merged** SV count within 1% of LibSVM at eps=0.001 "
        "— the tolerance of the reference's own parity claim (reference "
        "README.md:23,27). Merging sums alpha over identical (row, label) "
        "groups first: with duplicates the dual optimum is a face and the "
        "raw count is solver-path-dependent (on adult-shaped data LibSVM "
        "keeps ~9% more rows active, every one a duplicate of one of "
        "ours; merged counts match exactly). At the MNIST Makefile run's "
        "loose eps=0.01 the SV set is underdetermined by the stopping "
        "rule itself — LibSVM against itself moves 2.4% between tol=0.01 "
        "and 0.003 — so that pass reports counts as info and is judged "
        "on agreement.",
        "",
    ]
    for name in dict.fromkeys(r["dataset"] for r in rows):
        for tag in dict.fromkeys(r["phase"] for r in rows
                                 if r["dataset"] == name):
            sub = [r for r in rows
                   if r["dataset"] == name and r["phase"] == tag]
            r0 = sub[0]
            sv_note = ("SV parity asserted" if r0["check_sv"]
                       else "SV counts informational (loose eps)")
            lines += [
                f"## {name} / {tag} (n={r0['n']}, eps={r0['eps']}; "
                f"{sv_note})",
                "",
                f"LibSVM: **{r0['sk_sv']} SVs** ({r0['sk_msv']} merged), "
                f"train accuracy {r0['sk_acc']:.4f}.",
                "",
                "| engine/backend | platform | n_sv | merged | Δmerged | "
                "sign agree | train acc | pair updates | device s | "
                "status |",
                "|---|---|---|---|---|---|---|---|---|---|",
            ]
            for r in sub:
                lines.append(
                    f"| {r['case']} | {r['platform']} | {r['n_sv']} | "
                    f"{r['msv']} | {r['sv_dev'] * 100:.2f}% | "
                    f"{r['agree'] * 100:.2f}% | {r['acc']:.4f} | "
                    f"{r['iterations']} | {r['device_seconds']} | "
                    f"{'OK' if r['ok'] else '**FAIL**'} |")
            lines.append("")
    # Preserve the sections other harnesses maintain surgically
    # (tools/parity60k.py's full-scale section, tools/parity_covtype.py's
    # covtype section — both use parity_common.replace_section): a
    # mid-scale refresh must never clobber their measured artifacts.
    from tools.parity_common import preserved_tail

    keep = preserved_tail(open(path).read()) if os.path.exists(path) else ""
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        fh.write("\n".join(lines))
        if keep:
            fh.write("\n" + keep)
    os.replace(tmp, path)


if __name__ == "__main__":
    sys.exit(main())
