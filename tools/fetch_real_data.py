"""Real-dataset recipe: download, verify, convert — one command.

The parity/bench claims about "MNIST-shaped" and "covtype-shaped" runs
use synthetic stand-ins because this environment ships no datasets and
(usually) no egress (VERDICT gap 1). This tool makes the REAL runs one
command away the day egress is available:

    python tools/fetch_real_data.py            # fetch + verify + convert
    python tools/fetch_real_data.py --check    # report what's present
    make fetch_real_data

Per dataset it downloads the upstream files, verifies sha256 checksums,
runs the existing converters (dpsvm_tpu/data/converters.py) into the
reference CSV formats under data/, and exits 0 with a clean SKIP
message when the network is unreachable — so CI and cron runs never
fail on a sealed environment. Consumers activate their real-data legs
only when the converted files exist (tests/test_real_data.py skips
cleanly otherwise — the same contract as the TPU-reachability
preflight).

Checksum policy: pins marked RECORD_ON_FIRST_FETCH could not be
verified from inside this sealed environment; the first fetch PRINTS
the observed sha256 and refuses to report the file VERIFIED until the
value is committed here. MNIST's pins are the widely mirrored ones.
"""

from __future__ import annotations

import argparse
import gzip
import hashlib
import os
import sys
import urllib.error
import urllib.request

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

DATA = os.path.join(REPO, "data")
RAW = os.path.join(DATA, "raw")

RECORD_ON_FIRST_FETCH = None  # sentinel: pin after the first real fetch

# (url, sha256-or-None). MNIST via the ossci S3 mirror (the original
# yann.lecun.com host 403s unauthenticated fetches); covtype from UCI;
# Adult a9a from the LIBSVM dataset page (reference Makefile:83 shape).
SOURCES = {
    "mnist-train-images": (
        "https://ossci-datasets.s3.amazonaws.com/mnist/"
        "train-images-idx3-ubyte.gz",
        "440fcabf73cc546fa21475e81ea370265605f56be210a4024d2ca8f203523609"),
    "mnist-train-labels": (
        "https://ossci-datasets.s3.amazonaws.com/mnist/"
        "train-labels-idx1-ubyte.gz",
        "3552534a0a558bbed6aed32b30c495cca23d567ec52cac8be1a0730e8010255c"),
    "mnist-test-images": (
        "https://ossci-datasets.s3.amazonaws.com/mnist/"
        "t10k-images-idx3-ubyte.gz",
        "8d422c7b0a1c1c79245a5bcf07fe86e33eeafee792b84584aec276f5a2dbc4e6"),
    "mnist-test-labels": (
        "https://ossci-datasets.s3.amazonaws.com/mnist/"
        "t10k-labels-idx1-ubyte.gz",
        "f7ae60f92e00ec6debd23a6088c31dbd2371eca3ffa0defaefb259924204aec6"),
    "covtype": (
        "https://archive.ics.uci.edu/static/public/31/covertype.zip",
        RECORD_ON_FIRST_FETCH),
    "adult-a9a-train": (
        "https://www.csie.ntu.edu.tw/~cjlin/libsvmtools/datasets/"
        "binary/a9a",
        RECORD_ON_FIRST_FETCH),
    "adult-a9a-test": (
        "https://www.csie.ntu.edu.tw/~cjlin/libsvmtools/datasets/"
        "binary/a9a.t",
        RECORD_ON_FIRST_FETCH),
}

# Converted artifacts (the files consumers gate on).
CONVERTED = {
    "mnist_odd_even_train": os.path.join(DATA, "mnist_odd_even_train.csv"),
    "mnist_odd_even_test": os.path.join(DATA, "mnist_odd_even_test.csv"),
    "mnist_digits_train": os.path.join(DATA, "mnist_digits_train.csv"),
    "mnist_digits_test": os.path.join(DATA, "mnist_digits_test.csv"),
    "covtype_multiclass": os.path.join(DATA, "covtype_multiclass.csv"),
    "covtype_binary": os.path.join(DATA, "covtype_binary.csv"),
    "adult_train": os.path.join(DATA, "adult_train.csv"),
    "adult_test": os.path.join(DATA, "adult_test.csv"),
}


def real_data_available(*names: str) -> bool:
    """Whether the named converted artifacts (default: any) exist —
    THE gate consumers use to activate real-data legs."""
    paths = ([CONVERTED[n] for n in names] if names
             else list(CONVERTED.values()))
    return all(os.path.exists(p) for p in paths)


def _sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as fh:
        for chunk in iter(lambda: fh.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _fetch(name: str, timeout: float) -> str | None:
    """Download + checksum one source into data/raw. Returns the local
    path, or None on a (clean-skip) network failure; raises on a
    checksum MISMATCH (corrupt download is an error, not a skip)."""
    url, want = SOURCES[name]
    os.makedirs(RAW, exist_ok=True)
    local = os.path.join(RAW, url.rsplit("/", 1)[-1])
    if not os.path.exists(local):
        tmp = local + ".part"
        try:
            with urllib.request.urlopen(url, timeout=timeout) as r, \
                    open(tmp, "wb") as fh:
                while True:
                    chunk = r.read(1 << 20)
                    if not chunk:
                        break
                    fh.write(chunk)
        except (urllib.error.URLError, OSError, TimeoutError) as e:
            print(f"  SKIP {name}: {url} unreachable ({e})")
            if os.path.exists(tmp):
                os.remove(tmp)
            return None
        os.replace(tmp, local)
    got = _sha256(local)
    if want is RECORD_ON_FIRST_FETCH:
        print(f"  FETCHED {name}: sha256 {got} is UNPINNED — verify it "
              f"out-of-band and commit it in SOURCES[{name!r}] before "
              "publishing numbers from this file")
    elif got != want:
        raise RuntimeError(
            f"{name}: sha256 mismatch for {local}\n  want {want}\n"
            f"  got  {got}\n(corrupt or tampered download; delete the "
            "file and re-fetch)")
    else:
        print(f"  VERIFIED {name}: sha256 {got[:16]}…")
    return local


def _read_idx(path: str) -> np.ndarray:
    """Parse an (gzipped) IDX file — the MNIST container format."""
    with gzip.open(path, "rb") as fh:
        raw = fh.read()
    magic = int.from_bytes(raw[0:4], "big")
    ndim = magic & 0xFF
    dims = [int.from_bytes(raw[4 + 4 * i:8 + 4 * i], "big")
            for i in range(ndim)]
    return (np.frombuffer(raw, np.uint8, offset=4 + 4 * ndim)
            .reshape(dims))


def _write_csv(path: str, x: np.ndarray, y: np.ndarray) -> None:
    from dpsvm_tpu.data.loader import save_csv
    save_csv(path, np.asarray(x, np.float32), y)
    print(f"  wrote {os.path.relpath(path, REPO)}: "
          f"{x.shape[0]} x {x.shape[1]}")


def _convert_mnist(files: dict) -> None:
    from dpsvm_tpu.data.converters import mnist_to_odd_even
    for split in ("train", "test"):
        img_k, lab_k = f"mnist-{split}-images", f"mnist-{split}-labels"
        if not (files.get(img_k) and files.get(lab_k)):
            continue
        x = _read_idx(files[img_k]).reshape(-1, 784)
        digits = _read_idx(files[lab_k])
        # Even/odd binary relabelling (the reference's benchmark task,
        # scripts/convert_mnist_to_odd_even.py) ...
        xs, y = mnist_to_odd_even(x, digits)
        _write_csv(CONVERTED[f"mnist_odd_even_{split}"], xs, y)
        # ... plus the raw 10-digit labels for the multiclass/serving
        # paths (models/multiclass.py, serve.py).
        _write_csv(CONVERTED[f"mnist_digits_{split}"], x / 255.0,
                   digits.astype(np.int32))


def _convert_covtype(local: str) -> None:
    import io
    import zipfile
    with zipfile.ZipFile(local) as zf:
        inner = next(n for n in zf.namelist()
                     if n.endswith("covtype.data.gz"))
        raw = gzip.decompress(zf.read(inner))
    arr = np.loadtxt(io.BytesIO(raw), delimiter=",", dtype=np.float32)
    x, labels = arr[:, :54], arr[:, 54].astype(np.int32)  # 1..7
    _write_csv(CONVERTED["covtype_multiclass"], x, labels)
    # The reference's binary stress task: class 2 vs rest
    # (BENCH_COVTYPE.md's convention).
    _write_csv(CONVERTED["covtype_binary"], x,
               np.where(labels == 2, 1, -1).astype(np.int32))


def _convert_adult(files: dict) -> None:
    from dpsvm_tpu.data.converters import libsvm_to_csv
    for key, out in (("adult-a9a-train", "adult_train"),
                     ("adult-a9a-test", "adult_test")):
        if files.get(key):
            # The reference pins Adult to 123 features (Makefile:83).
            n, d = libsvm_to_csv(files[key], CONVERTED[out],
                                 num_features=123)
            print(f"  wrote {os.path.relpath(CONVERTED[out], REPO)}: "
                  f"{n} x {d}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--check", action="store_true",
                    help="report present raw/converted files; no network")
    ap.add_argument("--timeout", type=float, default=30.0,
                    help="per-download timeout seconds (default 30)")
    ap.add_argument("--only", choices=["mnist", "covtype", "adult"],
                    default=None, help="fetch one dataset family only")
    args = ap.parse_args(argv)

    if args.check:
        for name, path in CONVERTED.items():
            state = "present" if os.path.exists(path) else "missing"
            print(f"  {name}: {state} ({os.path.relpath(path, REPO)})")
        print("real-data legs " +
              ("ACTIVE" if real_data_available() else
               "inactive (run this tool with egress to activate)"))
        return 0

    os.makedirs(DATA, exist_ok=True)
    fam = args.only
    files: dict = {}
    any_skip = False
    for name in SOURCES:
        if fam and not name.startswith(
                {"mnist": "mnist", "covtype": "covtype",
                 "adult": "adult"}[fam]):
            continue
        local = _fetch(name, args.timeout)
        files[name] = local
        any_skip |= local is None

    if (not fam or fam == "mnist"):
        _convert_mnist(files)
    if (not fam or fam == "covtype") and files.get("covtype"):
        _convert_covtype(files["covtype"])
    if (not fam or fam == "adult"):
        _convert_adult(files)

    if any_skip:
        print("SKIP: some sources were unreachable (sealed environment?) "
              "— exit 0 by design; re-run when egress is available")
    else:
        print("all requested datasets fetched, verified and converted")
    return 0


if __name__ == "__main__":
    sys.exit(main())
