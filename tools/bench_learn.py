"""Continuous-learning benchmark: warm-start vs cold retraining A/B.

The warm-start machinery (solver/warmstart.py + solver/cascade.py,
ISSUE 18) claims that retraining an increment FROM THE PREVIOUS
GENERATION'S SUPPORT VECTORS reaches cold-start accuracy with fewer
optimization pairs and less wall clock.  This tool measures that claim
three ways:

* **Increment A/B** (the headline): train generation 0 cold on an
  MNIST-shaped synthetic base (d=784), form the continuous-learning
  increment ``concat(gen0 SVs, fresh drifted rows)``, and solve it
  BOTH ways — cold from scratch vs warm through the cascade.  Both
  legs see the IDENTICAL increment (drift matched by construction —
  the drift-normalization the cross-session gate needs), and the A/B
  only counts if both models reach the same held-out accuracy within
  the stated tolerance.  Headline metric: percent pairs saved.
* **C-sweep walk**: ``svc_c_sweep(..., warm=True)`` across a >=5-point
  C grid vs the cold fleet sweep — total-pairs cut at per-C prediction
  agreement.
* **Drifting-distribution serving leg**: a live ServingEngine serves
  the generation-0 model under closed-loop load (tools/loadgen.py
  closed_loop) while the loop retrains generation 1 warm on drifted
  rows and hot-swaps it in at the halfway point — the acceptance
  contract is ZERO failed/lost requests across the mid-traffic swap.

Writes BENCH_LEARN_r<NN>.json at the repo root (commit it — the
artifact, not the commit message, is the evidence) and REWRITES
BENCH_LEARN.md.  The headline pairs-cut percent runs through the same
drift-normalized cross-session regression gate as every other bench
family (bench._regression_gate over BENCH_LEARN_r*.json).  Pair counts
are platform-independent; wall clocks on a CPU harness carry
device_numbers=pending until a TPU session re-runs this tool.

Run: `python tools/bench_learn.py [--rows N] [--d D]`.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _accuracy(model, x, y) -> float:
    import importlib

    predict = importlib.import_module("dpsvm_tpu.predict")
    return float((predict.predict(model, x) == np.asarray(y)).mean())


def _increment_ab(rows: int, d: int, drift: float, acc_tol: float,
                  seed: int = 5) -> dict:
    """The headline A/B: one warm-started increment retrain vs the cold
    solve of the identical increment, at matched held-out accuracy."""
    from dpsvm_tpu.config import SVMConfig
    from dpsvm_tpu.learn import synthetic_stream
    from dpsvm_tpu.models.svm_model import SVMModel
    from dpsvm_tpu.ops.kernels import KernelParams
    from dpsvm_tpu.solver.cascade import cascade_solve
    from dpsvm_tpu.solver.smo import solve
    from dpsvm_tpu.solver.warmstart import seed_from_model

    cfg = SVMConfig(c=1.0, gamma=1.0 / d, epsilon=1e-3,
                    max_iter=200_000)
    kp = KernelParams("rbf", 1.0 / d)
    gens = list(synthetic_stream(seed, d, rows, 3, drift))
    (x0, y0), (x1, y1), (xt, yt) = gens  # base, fresh, held-out test

    t0 = time.perf_counter()
    r0 = solve(x0, y0, cfg)
    gen0_seconds = time.perf_counter() - t0
    m0 = SVMModel.from_dense(x0, y0, r0.alpha, r0.b, kp)

    x_inc = np.concatenate([np.asarray(m0.sv_x, np.float32), x1])
    y_inc = np.concatenate([np.asarray(m0.sv_y, np.int32), y1])

    t0 = time.perf_counter()
    cold = solve(x_inc, y_inc, cfg)
    cold_seconds = time.perf_counter() - t0
    t0 = time.perf_counter()
    warm, st = cascade_solve(x_inc, y_inc, cfg,
                             seed=seed_from_model(m0))
    warm_seconds = time.perf_counter() - t0

    mc = SVMModel.from_dense(x_inc, y_inc, cold.alpha, cold.b, kp)
    mw = SVMModel.from_dense(x_inc, y_inc, warm.alpha, warm.b, kp)
    acc_cold = _accuracy(mc, xt, yt)
    acc_warm = _accuracy(mw, xt, yt)
    pairs_cold = int(cold.iterations)
    pairs_warm = int(st["total_iterations"])
    return {
        "rows_base": int(x0.shape[0]), "rows_fresh": int(x1.shape[0]),
        "rows_increment": int(x_inc.shape[0]), "d": int(d),
        "drift_radians_per_generation": float(drift),
        "gen0_pairs": int(r0.iterations),
        "gen0_seconds": round(gen0_seconds, 4),
        "seed_sv": int(m0.sv_x.shape[0]),
        "pairs_cold": pairs_cold, "pairs_warm": pairs_warm,
        "pairs_saved": pairs_cold - pairs_warm,
        "pairs_cut_percent": round(
            100.0 * (1.0 - pairs_warm / pairs_cold), 2),
        "wall_seconds_cold": round(cold_seconds, 4),
        "wall_seconds_warm": round(warm_seconds, 4),
        "wall_cut_percent": round(
            100.0 * (1.0 - warm_seconds / cold_seconds), 2),
        "holdout_accuracy_cold": round(acc_cold, 4),
        "holdout_accuracy_warm": round(acc_warm, 4),
        "accuracy_tolerance": acc_tol,
        "accuracy_matched": bool(abs(acc_warm - acc_cold) <= acc_tol),
        "warm_start_stats": warm.stats.get("warm_start"),
    }


def _c_sweep_ab(seed: int = 6) -> dict:
    """Warm regularization-path walk vs the cold fleet sweep across a
    5-point C grid: total pairs, per-C prediction agreement."""
    from dpsvm_tpu.estimators import svc_c_sweep

    rng = np.random.default_rng(seed)
    n, d = 512, 16
    x = rng.normal(size=(n, d)).astype(np.float32)
    y = np.where(x[:, 0] + 0.4 * rng.normal(size=n) > 0, 1, -1)
    xt = rng.normal(size=(512, d)).astype(np.float32)
    Cs = [0.1, 0.3, 1.0, 3.0, 10.0]

    t0 = time.perf_counter()
    cold = svc_c_sweep(x, y, Cs, backend="single", gamma=1.0 / d)
    cold_seconds = time.perf_counter() - t0
    t0 = time.perf_counter()
    warm = svc_c_sweep(x, y, Cs, warm=True, backend="single",
                       gamma=1.0 / d)
    warm_seconds = time.perf_counter() - t0

    pairs_cold = int(sum(e.n_iter_ for e in cold))
    pairs_warm = int(sum(e.n_iter_ for e in warm))
    agreement = [round(float((c.predict(xt) == w.predict(xt)).mean()), 4)
                 for c, w in zip(cold, warm)]
    return {
        "n": n, "d": d, "Cs": Cs,
        "pairs_per_c_cold": [int(e.n_iter_) for e in cold],
        "pairs_per_c_warm": [int(e.n_iter_) for e in warm],
        "pairs_cold_total": pairs_cold, "pairs_warm_total": pairs_warm,
        "pairs_cut_percent": round(
            100.0 * (1.0 - pairs_warm / pairs_cold), 2),
        "wall_seconds_cold": round(cold_seconds, 4),
        "wall_seconds_warm": round(warm_seconds, 4),
        "prediction_agreement_per_c": agreement,
        "min_agreement": min(agreement),
    }


def _drift_serving_leg(tmp: str, requests: int, seed: int = 7) -> dict:
    """The live loop under load: generation 0 serves while generation 1
    retrains warm on drifted rows and hot-swaps in mid-traffic.  Zero
    failed/lost requests across the swap is the acceptance contract."""
    from tools.loadgen import closed_loop

    from dpsvm_tpu.config import ServeConfig, SVMConfig
    from dpsvm_tpu.learn import synthetic_stream, train_generation
    from dpsvm_tpu.ops.kernels import KernelParams
    from dpsvm_tpu.serving import ServingEngine

    d = 24
    cfg = SVMConfig(c=1.0, gamma=1.0 / d, epsilon=1e-3,
                    max_iter=100_000)
    kp = KernelParams("rbf", 1.0 / d)
    gens = list(synthetic_stream(seed, d, 384, 2, 0.15))
    model0, info0 = train_generation(None, gens[0][0], gens[0][1],
                                     cfg, kp)
    p0 = os.path.join(tmp, "gen_0000.npz")
    model0.save(p0)

    engine = ServingEngine(ServeConfig(buckets=(64,)))
    try:
        engine.register("learn", p0)
        swap_info = {}

        def retrain_and_swap():
            t0 = time.perf_counter()
            model1, info1 = train_generation(
                model0, gens[1][0], gens[1][1], cfg, kp,
                cold_baseline=True)
            p1 = os.path.join(tmp, "gen_0001.npz")
            model1.save(p1)
            engine.swap("learn", p1)
            engine.metrics.counter("learn.generations_total").add(1)
            engine.metrics.counter("learn.pairs_total").add(
                info1["pairs"])
            engine.metrics.counter("learn.pairs_saved_total").add(
                max(0, info1["pairs_saved"]))
            swap_info.update(
                gen1_pairs=info1["pairs"],
                gen1_pairs_cold=info1["pairs_cold"],
                gen1_pairs_saved=info1["pairs_saved"],
                retrain_and_swap_seconds=round(
                    time.perf_counter() - t0, 4))

        leg = closed_loop(engine, requests, concurrency=4,
                          sizes=[1, 4, 16], traffic=[("learn", 1.0)],
                          seed=seed, swap_at=0.5,
                          swap_fn=retrain_and_swap)
        snap_counters = {
            name: engine.metrics.counter(name).value
            for name in ("learn.generations_total", "learn.pairs_total",
                         "learn.pairs_saved_total")}
    finally:
        engine.close()
    return {
        "gen0_pairs": info0["pairs"],
        "swap": swap_info,
        "loadgen": {k: leg[k] for k in
                    ("requests", "rows", "wall_seconds",
                     "rows_per_second", "verdicts", "failed",
                     "deadline_misses")},
        "learn_metrics": snap_counters,
        "zero_loss_across_swap": bool(
            leg["failed"] == 0 and leg["verdicts"]["failed"] == 0),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--rows", type=int, default=768,
                    help="base rows for the MNIST-shaped increment A/B "
                         "(CPU-harness friendly default; raise on TPU)")
    ap.add_argument("--d", type=int, default=784)
    ap.add_argument("--drift", type=float, default=0.1,
                    help="radians of boundary rotation per generation")
    ap.add_argument("--acc-tol", type=float, default=0.02,
                    help="matched-accuracy tolerance for the A/B")
    ap.add_argument("--requests", type=int, default=96,
                    help="closed-loop requests for the serving leg")
    args = ap.parse_args(argv)

    import tempfile

    import jax

    import bench

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    calibration = bench._session_calibration()
    print(f"[bench_learn] device={dev} "
          f"calibration={json.dumps(calibration)}", file=sys.stderr)

    ab = _increment_ab(args.rows, args.d, args.drift, args.acc_tol)
    print(f"[bench_learn] increment A/B: cold={ab['pairs_cold']} "
          f"warm={ab['pairs_warm']} pairs "
          f"({ab['pairs_cut_percent']}% cut, "
          f"wall {ab['wall_cut_percent']}% cut), acc "
          f"{ab['holdout_accuracy_cold']} vs "
          f"{ab['holdout_accuracy_warm']}", file=sys.stderr)
    assert ab["accuracy_matched"], ab
    assert ab["pairs_saved"] > 0, ab
    assert ab["wall_cut_percent"] > 0, ab

    sweep = _c_sweep_ab()
    print(f"[bench_learn] C-sweep walk: "
          f"cold={sweep['pairs_cold_total']} "
          f"warm={sweep['pairs_warm_total']} pairs "
          f"({sweep['pairs_cut_percent']}% cut), min agreement "
          f"{sweep['min_agreement']}", file=sys.stderr)
    assert sweep["pairs_warm_total"] < sweep["pairs_cold_total"], sweep
    assert sweep["min_agreement"] >= 0.98, sweep

    with tempfile.TemporaryDirectory() as tmp:
        drift_leg = _drift_serving_leg(tmp, args.requests)
    print(f"[bench_learn] drifting serving leg: "
          f"{drift_leg['loadgen']['rows_per_second']} rows/s, "
          f"swap saved {drift_leg['swap'].get('gen1_pairs_saved')} "
          f"pairs, zero_loss={drift_leg['zero_loss_across_swap']}",
          file=sys.stderr)
    assert drift_leg["zero_loss_across_swap"], drift_leg

    result = {
        "metric": ("warm-start increment retraining vs cold, "
                   f"MNIST-shaped synth (d={args.d}, "
                   f"base={ab['rows_base']} rows, increment="
                   f"{ab['rows_increment']} rows, drift="
                   f"{args.drift} rad/gen), pairs saved at matched "
                   f"held-out accuracy (tol {args.acc_tol})"),
        "value": ab["pairs_cut_percent"],
        "unit": "percent pairs saved vs cold",
        "pairs_cut_percent": ab["pairs_cut_percent"],
        "increment_ab": ab,
        "c_sweep": sweep,
        "drift_serving": drift_leg,
        **bench._device_fields(),
        "device_numbers": ("measured" if on_tpu else
                           "pending — no TPU reachable this session; "
                           "pair counts are platform-independent, "
                           "CPU-harness wall clocks are directional"),
        "schema_version": bench._schema_version(),
        "session_calibration": calibration,
    }
    gate = bench._regression_gate(result, REPO,
                                  pattern="BENCH_LEARN_r*.json",
                                  key="pairs_cut_percent")
    result.update(gate)
    print(f"[bench_learn] regression gate: "
          f"{gate.get('regression_gate')}", file=sys.stderr)

    nn = len(glob.glob(os.path.join(REPO, "BENCH_LEARN_r*.json"))) + 1
    art = os.path.join(REPO, f"BENCH_LEARN_r{nn:02d}.json")
    with open(art, "w") as fh:
        json.dump(result, fh, indent=1)
    print(json.dumps({k: result[k] for k in
                      ("metric", "value", "unit", "regression_gate")}))

    with open(os.path.join(REPO, "BENCH_LEARN.md"), "w") as fh:
        fh.write(
            "# BENCH_LEARN — cascade warm-start continuous learning\n\n"
            "Command: `python tools/bench_learn.py` (artifact "
            f"`{os.path.basename(art)}`; history lives in git). "
            "Warm-started increment retraining (solver/warmstart.py + "
            "solver/cascade.py) A/B'd against cold retraining of the "
            "IDENTICAL increment — drift matched by construction, "
            "counted only at matched held-out accuracy. Pair counts "
            "are platform-independent; wall clocks on a CPU harness "
            "carry device_numbers=pending until a TPU session re-runs "
            "this tool.\n\n"
            "## Increment A/B (headline)\n\n"
            "| leg | pairs | wall s | held-out acc |\n|---|---|---|---|\n"
            f"| cold | {ab['pairs_cold']} | "
            f"{ab['wall_seconds_cold']} | "
            f"{ab['holdout_accuracy_cold']} |\n"
            f"| warm | {ab['pairs_warm']} | "
            f"{ab['wall_seconds_warm']} | "
            f"{ab['holdout_accuracy_warm']} |\n\n"
            f"**{ab['pairs_cut_percent']}% pairs saved, "
            f"{ab['wall_cut_percent']}% wall saved** (seed "
            f"{ab['seed_sv']} SVs into a "
            f"{ab['rows_increment']}-row increment).\n\n"
            "## C-sweep regularization-path walk\n\n"
            f"Cs={sweep['Cs']}: cold fleet "
            f"{sweep['pairs_cold_total']} pairs, warm walk "
            f"{sweep['pairs_warm_total']} pairs "
            f"(**{sweep['pairs_cut_percent']}% cut**), per-C "
            f"prediction agreement >= {sweep['min_agreement']}.\n\n"
            "## Drifting-distribution serving leg\n\n```json\n"
            + json.dumps(drift_leg, indent=1)
            + "\n```\n\n## Gate\n\n```json\n"
            + json.dumps({k: result[k] for k in
                          ("value", "unit", "device",
                           "device_numbers", "regression_gate")},
                         indent=1)
            + "\n```\n")
    print(f"[bench_learn] wrote {art} and BENCH_LEARN.md",
          file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
